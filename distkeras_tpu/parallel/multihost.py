"""Multi-host bring-up — ``jax.distributed`` in place of the Spark cluster.

The reference scales out by asking Spark for more executors; we scale out
by starting one identical process per TPU host (SURVEY.md §2 L0).  After
``initialize()``, ``jax.devices()`` spans the whole pod/slice, every mesh
built by the trainers is global, and the same SPMD programs run unchanged
— collectives ride ICI within a slice and DCN across slices.

Typical pod usage (same script on every host)::

    from distkeras_tpu.parallel import multihost
    multihost.initialize()                 # env-driven on TPU pods
    ds = multihost.local_shard(dataset)    # this host's partitions
    ADAG(model, ..., num_workers=jax.device_count()).train(ds)

The async-PS mode composes too: run the ``SocketParameterServer`` on
process 0 (it already listens on TCP/DCN) and point workers at
``coordinator host:port``.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Initialize the JAX multi-process runtime.

    On Cloud TPU pods all three arguments are discovered from the
    metadata/env automatically (pass nothing).  Explicit values mirror the
    reference's ``Punchcard`` host lists for manual clusters.  No-op when
    already initialized or single-process.

    MUST run before anything initializes the XLA backend (even
    ``jax.process_count()`` counts) — call it first thing in the program.
    """
    global _initialized
    if _initialized:
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = int(num_processes)
    if process_id is not None:
        kwargs["process_id"] = int(process_id)
    if not kwargs and "COORDINATOR_ADDRESS" in os.environ:
        kwargs["coordinator_address"] = os.environ["COORDINATOR_ADDRESS"]
        kwargs["num_processes"] = int(os.environ.get("NUM_PROCESSES", "1"))
        kwargs["process_id"] = int(os.environ.get("PROCESS_ID", "0"))
    try:
        jax.distributed.initialize(**kwargs)
        _initialized = True
    except ValueError:
        if kwargs:
            raise  # explicit config that failed is an error
        # auto mode on a machine with no coordinator configured: fine as a
        # single process.  _initialized stays False so a later explicit
        # call can still form the cluster.
    except RuntimeError as e:
        # a configured pod that failed to come up is ALWAYS an error —
        # swallowing it would let every host silently train the full
        # dataset independently.  The one benign RuntimeError in auto mode
        # is "backend already initialized / called too late" on a
        # single-process run, where there is nothing to form.
        msg = str(e).lower()
        benign = not kwargs and ("before" in msg or "already" in msg)
        if not benign:
            raise


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def local_shard(dataset):
    """This host's contiguous slice of a Dataset (one partition group per
    process) — the moral equivalent of Spark shipping each executor its
    partitions.  With P processes the dataset is repartitioned to a
    multiple of P and process k takes partitions [k·(n/P), (k+1)·(n/P)).
    """
    import numpy as np

    from ..data.dataset import Dataset

    p = jax.process_count()
    if p == 1:
        return dataset
    k = jax.process_index()
    # split row indices directly: robust to datasets smaller than the
    # process count (some hosts then get an empty shard rather than a
    # crash)
    per_rows = np.array_split(np.arange(dataset.num_rows), p)[k]
    cols = {name: dataset[name][per_rows]
            for name in dataset.column_names}
    per_parts = max(1, dataset.num_partitions // p)
    return Dataset(cols, num_partitions=per_parts)

"""Pipeline parallelism (GPipe schedule) over the ``pp`` mesh axis.

Absent from the reference (SURVEY.md §2 parallelism inventory: data
parallelism only) but first-class here: a stack of S structurally
identical stages is laid out one-stage-per-device along ``pp``; M
microbatches flow through the pipeline, activations hopping to the next
stage via ``lax.ppermute`` (neighbor traffic — rides ICI, never a host).

The whole schedule — fill, steady state, drain: M + S − 1 ticks — is ONE
``lax.scan`` inside ONE ``shard_map``-ed jit program, so XLA sees a
static loop and overlaps each tick's compute with the activation
ppermute.  Bubble ticks compute on garbage and are masked out of the
result (the classic GPipe trade: bubble fraction (S−1)/(M+S−1); raise M
to amortize).  Reverse-mode AD simply runs the scan backward —
activations re-flow through the inverse permutation, giving backward
pipelining without any hand-written schedule.

Stage contract: ``stage_fn(stage_params, x) -> y`` with ``x`` and ``y``
the same shape (homogeneous blocks — transformer layers, residual MLP
blocks).  This is the standard constraint of SPMD pipelining: one
program runs on every device, so every stage must be the same program
with different weights.

Ref (pattern): jax shard_map pipelining idiom; GPipe (Huang et al. 2019)
for the schedule.  No reference-code equivalent exists (SURVEY.md §2:
strategy ABSENT upstream).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map
from .sync import _shard_map_kw

Tree = Any


def stack_stage_params(stage_params: Sequence[Tree]) -> Tree:
    """Stack S per-stage param pytrees into one tree with a leading
    (stage,) axis — the layout ``pipeline_apply_sharded`` shards over
    ``pp``."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *stage_params)


def find_stage_segment(layers: Sequence, n_stages: int,
                       input_shape: Sequence[int] | None = None):
    """Locate the homogeneous stage segment of a Sequential layer list.

    Returns ``(start, group_len)`` such that
    ``layers[start : start + n_stages*group_len]`` splits into
    ``n_stages`` structurally identical groups (class + full config
    equality, nested layers included) — e.g. ``zoo.gpt_lm``'s repeated
    (Residual-attention, FF) blocks.  Picks the longest such span.
    Raises when the stack has none (the model cannot pipeline over
    ``n_stages`` stages).

    ``input_shape`` (the model's per-sample input shape) enables the
    pp=1 fallback for stacks whose repeated unit occurs only ONCE
    (e.g. ``gpt_lm(num_blocks=1)``): with a single trivially-runnable
    stage, any shape-preserving span qualifies, so the longest one is
    chosen by tracking ``Layer.out_shape`` through the stack
    (ADVICE r4).
    """
    def sig(lyr):
        return (type(lyr).__name__, repr(lyr.config()))

    sigs = [sig(l) for l in layers]
    if n_stages == 1:
        # degenerate mesh (pp=1): "any span" would trivially qualify and
        # the longest-span rule would swallow embedding/head layers whose
        # shapes don't pipeline.  Anchor on the model's actual repeated
        # unit instead: locate it as a 2-stage split, then extend the run.
        try:
            a, g = find_stage_segment(layers, 2)
        except ValueError:
            # no repeated unit at all (e.g. a single transformer block):
            # fall back to the longest shape-preserving span — pp=1 runs
            # it as the one stage with no schedule constraints beyond
            # shape preservation (state/rng checks stay with the caller)
            if input_shape is None:
                raise ValueError(
                    "pp=1 with no repeated layer group: pass input_shape "
                    "so the stage segment can be chosen by shape "
                    "preservation, or raise num_blocks so the repeated "
                    "unit occurs at least twice")
            shapes = [tuple(input_shape)]
            for lyr in layers:
                shapes.append(tuple(lyr.out_shape(shapes[-1])))
            best = None
            for a in range(len(layers)):
                for end in range(len(layers), a, -1):
                    if shapes[a] == shapes[end]:
                        if best is None or end - a > best[1] - best[0]:
                            best = (a, end)
                        break
            if best is None:
                raise ValueError(
                    "pp=1 fallback found no shape-preserving span in "
                    "this stack; the model cannot pipeline")
            return best[0], best[1] - best[0]
        end = a + 2 * g
        while end + g <= len(layers) and sigs[end:end + g] == sigs[a:a + g]:
            end += g
        return a, end - a
    best = None
    for g in range(1, len(layers) // n_stages + 1):
        span = n_stages * g
        for a in range(0, len(layers) - span + 1):
            if all(sigs[a + i * g + j] == sigs[a + j]
                   for i in range(1, n_stages) for j in range(g)):
                if best is None or span > best[0]:
                    best = (span, a, g)
    if best is None:
        raise ValueError(
            f"no contiguous run of {n_stages} structurally identical "
            f"layer groups in this {len(layers)}-layer stack; pipeline "
            f"parallelism needs homogeneous stages (e.g. zoo.gpt_lm with "
            f"num_blocks divisible by the pp axis size)")
    return best[1], best[2]


def pipeline_apply(stage_fn: Callable, stage_params: Tree, x_mb, *,
                   axis_name: str = "pp"):
    """GPipe forward; call INSIDE ``shard_map``.

    ``stage_params``: this device's stage (leaves carry a leading
    singleton stage axis, as produced by a ``P(axis_name)`` in_spec on
    the stacked tree).  ``x_mb``: the full (M, mb, ...) microbatch stack,
    replicated.  Returns (M, mb, ...) outputs, replicated (psum'd off the
    last stage).
    """
    n_stages = lax.axis_size(axis_name)
    stage_idx = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    n_micro = x_mb.shape[0]
    ticks = n_micro + n_stages - 1
    fwd = [(j, j + 1) for j in range(n_stages - 1)]  # non-cyclic: 0 gets 0s

    # stage output aval: activations may promote past the token dtype
    # (bf16 tokens × f32 params → f32) — the carry/out buffers must live
    # in the promoted (fixed-point) dtype or the scan dtypes mismatch
    y_aval = jax.eval_shape(stage_fn, params,
                            jax.ShapeDtypeStruct(x_mb.shape[1:],
                                                 x_mb.dtype))
    y_aval = jax.eval_shape(stage_fn, params,
                            jax.ShapeDtypeStruct(x_mb.shape[1:],
                                                 y_aval.dtype))
    if y_aval.shape != x_mb.shape[1:]:
        raise ValueError(
            f"stage_fn must preserve the activation shape (homogeneous "
            f"stages): got {y_aval.shape} from {x_mb.shape[1:]}")

    def tick(carry, t):
        state, out = carry
        # stage 0 injects microbatch t while any remain; later stages use
        # the activation ppermuted in from the previous stage last tick
        inject = x_mb[jnp.clip(t, 0, n_micro - 1)].astype(y_aval.dtype)
        state = jnp.where((stage_idx == 0) & (t < n_micro), inject, state)
        y = stage_fn(params, state).astype(y_aval.dtype)
        # at tick t this stage holds microbatch m = t - stage_idx
        m = t - stage_idx
        is_last = stage_idx == n_stages - 1
        valid = is_last & (m >= 0) & (m < n_micro)
        mc = jnp.clip(m, 0, n_micro - 1)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(valid, y, lax.dynamic_index_in_dim(
                out, mc, keepdims=False)), mc, 0)
        state = lax.ppermute(y, axis_name, fwd)
        return (state, out), None

    state0 = jnp.zeros(x_mb.shape[1:], y_aval.dtype)
    out0 = jnp.zeros((n_micro,) + x_mb.shape[1:], y_aval.dtype)
    (_, out), _ = lax.scan(tick, (state0, out0), jnp.arange(ticks))
    # results live on the last stage only; broadcast so every device
    # returns the same (replicated) output
    return lax.psum(jnp.where(stage_idx == n_stages - 1, out, 0), axis_name)


def pipeline_apply_sharded(mesh: Mesh, stage_fn: Callable,
                           stacked_params: Tree, x, *,
                           num_microbatches: int, axis: str = "pp",
                           dp_axis: str | None = None):
    """Whole-array entry point: run S = ``mesh.shape[axis]`` stages over
    the pipeline.  ``stacked_params``: leading (S, ...) stage axis on
    every leaf (see :func:`stack_stage_params`).  ``x``: (B, ...) with B
    divisible by ``num_microbatches``.  Returns (B, ...).

    ``dp_axis``: optional second mesh axis to ALSO shard each
    microbatch's batch dim over — pp×dp composition: every dp replica
    runs the same pipeline schedule on its slice of every microbatch
    (params replicated across ``dp_axis``; the caller's grad psum over
    ``dp_axis`` falls out of AD through the sharded batch)."""
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by "
                         f"num_microbatches {num_microbatches}")
    lead = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if lead != n_stages:
        raise ValueError(f"stacked_params lead dim {lead} != pipeline "
                         f"stages {n_stages} (mesh axis {axis!r})")
    mb = batch // num_microbatches
    if dp_axis is not None and mb % mesh.shape[dp_axis]:
        raise ValueError(f"microbatch size {mb} not divisible by the "
                         f"{dp_axis!r} axis size {mesh.shape[dp_axis]}")
    x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])
    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    data_spec = P(None, dp_axis) if dp_axis is not None else P()
    fn = shard_map(
        partial(pipeline_apply, stage_fn, axis_name=axis),
        mesh=mesh,
        in_specs=(param_specs, data_spec),
        out_specs=data_spec,
        **_shard_map_kw())
    out = fn(stacked_params, x_mb)
    return out.reshape(batch, *out.shape[2:])

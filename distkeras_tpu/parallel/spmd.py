"""GSPMD sharding: multi-axis (dp × mp) training via sharding annotations.

The scaling-book recipe, applied: pick a mesh, annotate parameter and batch
shardings, let XLA insert the collectives.  Nothing here exchanges weights
explicitly — data parallelism falls out of the batch being sharded on
``dp`` (XLA all-reduces the grads), tensor parallelism out of large kernels
being sharded on ``mp`` (XLA partitions the matmuls and inserts
all-gather/reduce-scatter where profitable, riding ICI).

This is the forward-looking path beyond the reference's pure data
parallelism (its only strategy, SURVEY.md §2) — model families too large
to replicate per chip (e.g. ResNet-50 heads, transformer stacks) shard
here with no model-code changes.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Tree = Any


def infer_param_specs(params: Tree, mesh: Mesh, tp_axis: str = "mp",
                      min_size: int = 2048) -> Tree:
    """Heuristic tensor-parallel sharding rules.

    For each parameter: shard its largest SHARDABLE dimension over
    ``tp_axis`` when (a) the dim is divisible by the axis size and (b)
    the tensor is big enough to be worth the collectives; otherwise
    replicate.  Biases and norm scales stay replicated.  4-D conv
    kernels (HWIO layout) restrict candidates to the trailing I/O
    channel dims — sharding a spatial extent would split the stencil
    XLA convolves over, forcing halo exchanges for a dim that is rarely
    divisible anyway (VERDICT r4 weak #6).  XLA's SPMD partitioner
    propagates the rest (activations, grads, opt state).
    """
    if tp_axis not in mesh.axis_names:
        return jax.tree_util.tree_map(lambda _: P(), params)
    tp = mesh.shape[tp_axis]

    def spec(leaf):
        shape = np.shape(leaf)
        if len(shape) < 2 or np.prod(shape) < min_size:
            return P()
        # conv kernels: consider only the channel dims (last two)
        dims = range(len(shape) - 2, len(shape)) if len(shape) == 4 \
            else range(len(shape))
        best = max((d for d in dims if shape[d] % tp == 0),
                   key=lambda d: shape[d], default=None)
        if best is None:
            return P()
        parts = [None] * len(shape)
        parts[best] = tp_axis
        return P(*parts)

    return jax.tree_util.tree_map(spec, params)


def put(x, sharding):
    """Commit a host array to a (possibly multi-HOST) sharding.

    Single-process meshes use plain ``device_put``.  When the mesh spans
    processes (``jax.distributed``), ``device_put`` cannot address remote
    devices; each process instead contributes exactly the global slices
    its own devices hold via ``make_array_from_callback`` — the
    executor-gets-its-partition contract (SURVEY.md §1 L0 / §3.1
    boundary #1) for the GSPMD trainers.  The host array is the same on
    every process (like the async cluster's dataset contract), and only
    this process's shards of it are materialized on device.
    """
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding,
                                        lambda idx: x[idx])


def place(tree: Tree, mesh: Mesh, specs: Tree):
    """Commit a pytree according to a PartitionSpec tree (multi-host
    aware — see :func:`put`)."""
    return jax.tree_util.tree_map(
        lambda x, s: put(x, NamedSharding(mesh, s)), tree, specs)


def replicate(tree: Tree, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda x: put(x, NamedSharding(mesh, P())), tree)


def batch_sharding(mesh: Mesh, dp_axis: str = "dp", batch_dim: int = 0):
    parts = [None] * (batch_dim + 1)
    parts[batch_dim] = dp_axis
    return NamedSharding(mesh, P(*parts))


class _ConstrainedForward:
    """Forward proxy pinning activation shardings (VERDICT r3 weak #3).

    ``with_sharding_constraint`` anchors the input batch and the output to
    ``P(dp_axis)`` on the leading (batch) dim; interior activations then
    propagate from the parameter specs.  Without these anchors a heuristic
    that silently replicated everything would still compile and pass
    numerical tests — the constraints make the intended sharding part of
    the traced program, and ``SpmdTrainer.compiled_step``/
    ``sharding_report`` make it inspectable.
    """

    def __init__(self, layer, mesh: Mesh, dp_axis: str):
        self.layer = layer
        self.mesh = mesh
        self.dp_axis = dp_axis

    def _pin(self, x):
        spec = P(self.dp_axis, *([None] * (x.ndim - 1)))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec))

    def apply(self, params, state, x, *, train=False, rng=None):
        y, new_state = self.layer.apply(params, state, self._pin(x),
                                        train=train, rng=rng)
        return self._pin(y), new_state


def constrained_model(model, mesh: Mesh, dp_axis: str = "dp"):
    """``model`` with its forward wrapped in activation sharding anchors;
    quacks enough like a Model for ``make_local_step`` (``.layer.apply``)."""
    import types
    proxy = types.SimpleNamespace()
    proxy.layer = _ConstrainedForward(model.layer, mesh, dp_axis)
    return proxy


def sharding_report(params_placed: Tree) -> dict:
    """Per-leaf placement audit: PartitionSpec, global vs per-device bytes.
    ``per_device_bytes < global_bytes`` is the direct evidence that mp
    actually sharded something (a replicated fallback shows equality)."""
    rows = {}
    total_global = total_per_device = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params_placed)[0]:
        per_dev = leaf.addressable_shards[0].data.nbytes
        rows[jax.tree_util.keystr(path)] = {
            "spec": str(getattr(leaf.sharding, "spec", leaf.sharding)),
            "global_bytes": int(leaf.nbytes),
            "per_device_bytes": int(per_dev)}
        total_global += int(leaf.nbytes)
        total_per_device += int(per_dev)
    return {"params": rows, "global_bytes": total_global,
            "per_device_bytes": total_per_device}

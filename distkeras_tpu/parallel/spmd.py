"""GSPMD sharding: multi-axis (dp × mp) training via sharding annotations.

The scaling-book recipe, applied: pick a mesh, annotate parameter and batch
shardings, let XLA insert the collectives.  Nothing here exchanges weights
explicitly — data parallelism falls out of the batch being sharded on
``dp`` (XLA all-reduces the grads), tensor parallelism out of large kernels
being sharded on ``mp`` (XLA partitions the matmuls and inserts
all-gather/reduce-scatter where profitable, riding ICI).

This is the forward-looking path beyond the reference's pure data
parallelism (its only strategy, SURVEY.md §2) — model families too large
to replicate per chip (e.g. ResNet-50 heads, transformer stacks) shard
here with no model-code changes.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Tree = Any


def infer_param_specs(params: Tree, mesh: Mesh, tp_axis: str = "mp",
                      min_size: int = 2048) -> Tree:
    """Heuristic tensor-parallel sharding rules.

    For each parameter: shard its largest dimension over ``tp_axis`` when
    (a) the dim is divisible by the axis size and (b) the tensor is big
    enough to be worth the collectives; otherwise replicate.  Biases and
    norm scales stay replicated.  XLA's SPMD partitioner propagates the
    rest (activations, grads, opt state).
    """
    if tp_axis not in mesh.axis_names:
        return jax.tree_util.tree_map(lambda _: P(), params)
    tp = mesh.shape[tp_axis]

    def spec(leaf):
        shape = np.shape(leaf)
        if len(shape) < 2 or np.prod(shape) < min_size:
            return P()
        dim = int(np.argmax(shape))
        if shape[dim] % tp != 0:
            return P()
        parts = [None] * len(shape)
        parts[dim] = tp_axis
        return P(*parts)

    return jax.tree_util.tree_map(spec, params)


def place(tree: Tree, mesh: Mesh, specs: Tree):
    """device_put a pytree according to a PartitionSpec tree."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs)


def replicate(tree: Tree, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)


def batch_sharding(mesh: Mesh, dp_axis: str = "dp", batch_dim: int = 0):
    parts = [None] * (batch_dim + 1)
    parts[batch_dim] = dp_axis
    return NamedSharding(mesh, P(*parts))

"""Chaos harness — fault injection for the self-healing fleet (ISSUE 9).

The reference inherited fault tolerance from Spark and never tested it;
this repo's PS stack now *implements* detect → down-weight → evict →
respawn (``ps.runner.FleetSupervisor``), so it needs a way to create the
faults on demand.  Three fault families, matching the three boundaries a
real fleet dies at:

* **process faults** — :func:`kill_worker` (SIGKILL: the OOM-killer /
  preempted-VM shape), :func:`pause_worker` / :func:`resume_worker`
  (SIGSTOP/SIGCONT: the wedged-but-alive shape).  For the
  ``async_workers="processes"`` placement, whose incarnations are real
  OS processes (``ps.worker_main``).
* **thread faults** — :class:`ThreadStall`, the in-process analogue of
  SIGSTOP for the ``threads`` placement (a single thread cannot be
  signal-stopped): the targeted worker's window call blocks on an event
  until :meth:`ThreadStall.resume`, exactly reproducing the
  wedged-but-alive liveness signature (pulls and commits stop reaching
  the PS while the thread stays alive).
* **socket faults** — :class:`SocketFaults`, a deterministic schedule of
  connection resets / timeouts injected through the process-wide seam in
  ``ps.networking`` (``set_fault_hook``) at the wire's choke points: the
  dial, the v1/v2 hello negotiation, and per-action sends (the commit
  path) / receives.

Every injector is a context manager that restores the world on exit; the
acceptance tests in ``tests/test_chaos.py`` assert the fleet converges
under each fault with exact commit accounting
(``requests == applied + dropped + tombstoned``).
"""

from __future__ import annotations

import os
import signal
import socket
import threading
from typing import Dict, Optional, Sequence

from .obs.logging import get_logger
from .ps import networking

_LOG = "chaos"


# ---------------------------------------------------------------------------
# process faults (the "processes" worker placement)
# ---------------------------------------------------------------------------

def _pid(proc_or_pid) -> int:
    return int(getattr(proc_or_pid, "pid", proc_or_pid))


def kill_worker(proc_or_pid) -> int:
    """SIGKILL a worker process (no cleanup, no goodbye — the OOM-killer
    shape).  Accepts a ``subprocess.Popen`` or a raw pid; returns the
    pid."""
    pid = _pid(proc_or_pid)
    get_logger(_LOG).warning("kill -9 worker process %d", pid)
    os.kill(pid, signal.SIGKILL)
    return pid


def pause_worker(proc_or_pid) -> int:
    """SIGSTOP a worker process: alive to the OS, dead to the fleet —
    the liveness signature the supervisor's heartbeat hard threshold
    exists to catch."""
    pid = _pid(proc_or_pid)
    get_logger(_LOG).warning("SIGSTOP worker process %d", pid)
    os.kill(pid, signal.SIGSTOP)
    return pid


def resume_worker(proc_or_pid) -> int:
    """SIGCONT a paused worker process.  By the time this runs the
    supervisor has typically evicted + replaced it — the revenant's next
    commit tombstones and it winds down cleanly."""
    pid = _pid(proc_or_pid)
    get_logger(_LOG).warning("SIGCONT worker process %d", pid)
    os.kill(pid, signal.SIGCONT)
    return pid


# ---------------------------------------------------------------------------
# thread faults (the "threads" worker placement)
# ---------------------------------------------------------------------------

class ThreadStall:
    """Virtual SIGSTOP for one thread-placement worker.

    Patches ``worker_cls._window`` so the targeted ``worker_id``'s
    incarnation at ``generation`` blocks on an internal event once it has
    completed ``stall_after`` windows — commits and pulls stop reaching
    the PS while the thread stays alive, the exact signature of a
    process SIGSTOP.  :meth:`resume` lifts the stall (the SIGCONT); the
    context manager restores the original ``_window`` on exit.

    The generation gate means the supervisor's replacement (which runs
    at the bumped generation) sails through untouched — only the
    incarnation the chaos targeted is wedged.
    """

    def __init__(self, worker_cls, worker_id: int, stall_after: int = 1,
                 generation: int = 0):
        self._cls = worker_cls
        self._orig = worker_cls._window
        self.worker_id = int(worker_id)
        self.stall_after = int(stall_after)
        self.generation = int(generation)
        self._resume_evt = threading.Event()
        self._stalled_evt = threading.Event()

    def __enter__(self) -> "ThreadStall":
        stall = self

        def stalled_window(wself, client, wx, wy):
            if (wself.worker_id == stall.worker_id
                    and wself.generation == stall.generation
                    and len(wself.window_losses) >= stall.stall_after
                    and not stall._resume_evt.is_set()):
                get_logger(_LOG).warning(
                    "stalling worker %d (thread) after %d windows",
                    wself.worker_id, len(wself.window_losses))
                stall._stalled_evt.set()
                stall._resume_evt.wait()
            return stall._orig(wself, client, wx, wy)

        self._cls._window = stalled_window
        return self

    def __exit__(self, *exc) -> None:
        self._cls._window = self._orig
        self._resume_evt.set()  # never leave a thread wedged past the test

    def wait_stalled(self, timeout: Optional[float] = None) -> bool:
        """Block until the target actually wedged (it hit the stall
        point); the chaos equivalent of watching ``ps`` say ``T``."""
        return self._stalled_evt.wait(timeout)

    def resume(self) -> None:
        """The SIGCONT: let the wedged incarnation run again (straight
        into its tombstoned commit, if the supervisor already replaced
        it)."""
        get_logger(_LOG).warning("resuming stalled worker %d (thread)",
                                 self.worker_id)
        self._resume_evt.set()


# ---------------------------------------------------------------------------
# socket faults (the v1/v2 negotiation and commit wire paths)
# ---------------------------------------------------------------------------

class SocketFaults:
    """Deterministic socket-fault schedule over ``ps.networking``'s
    fault seam.

    ``schedule`` maps a stage key to the 1-based call ordinals that
    fault.  Keys are the seam's stages — ``"connect"``, ``"handshake"``,
    ``"recv"`` — plus action-qualified sends: ``"send:commit"`` faults
    only commit sends, ``"send"`` faults every send.  Ordinals count per
    key, so ``{"send:commit": [3]}`` resets exactly the third commit any
    connection in this process attempts.

    ``kind`` picks the exception: ``"reset"`` (ConnectionResetError) or
    ``"timeout"`` (socket.timeout) — both travel the same OSError paths
    real kernels produce.  Thread-safe; counts and injections are
    inspectable (``calls``, ``injected``).  The context manager installs
    the hook on entry and restores the previous hook on exit.
    """

    def __init__(self, schedule: Dict[str, Sequence[int]],
                 kind: str = "reset"):
        if kind not in ("reset", "timeout"):
            raise ValueError(f"kind must be 'reset' or 'timeout', got "
                             f"{kind!r}")
        self.schedule = {str(k): set(int(i) for i in v)
                         for k, v in schedule.items()}
        self.kind = kind
        self.calls: Dict[str, int] = {}
        self.injected = 0
        self._lock = threading.Lock()
        self._prev = None
        self._installed = False

    def _raise(self, key: str, n: int):
        get_logger(_LOG).warning("injecting socket %s at %s call %d",
                                 self.kind, key, n)
        if self.kind == "timeout":
            raise socket.timeout(f"chaos: injected timeout ({key} #{n})")
        raise ConnectionResetError(f"chaos: injected reset ({key} #{n})")

    def __call__(self, stage: str, action=None) -> None:
        keys = [stage]
        if action is not None:
            keys.append(f"{stage}:{action}")
        fire = None
        with self._lock:
            for key in keys:
                if key not in self.schedule:
                    continue
                n = self.calls.get(key, 0) + 1
                self.calls[key] = n
                if n in self.schedule[key]:
                    self.injected += 1
                    fire = (key, n)
        if fire is not None:
            self._raise(*fire)

    def __enter__(self) -> "SocketFaults":
        self._prev = networking.set_fault_hook(self)
        self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            networking.set_fault_hook(self._prev)
            self._installed = False

"""dklint interprocedural core (ISSUE 18): the whole-repo graph.

PR 3's dklint reasons one file at a time; every rule that wants to see
across a call or an inheritance edge re-derives its own slice of the
project.  This module builds that structure ONCE per run and hands it to
``ProjectRule``s (``rules_project.py``):

* **modules** — every scanned file, keyed by dotted module name derived
  from its anchored relative path (``distkeras_tpu/serve/router.py`` ->
  ``distkeras_tpu.serve.router``), with its import table resolved
  (absolute, aliased, and package-relative ``from .. import`` forms).
* **class hierarchy** — classes with bases resolved through the import
  table to project classes where possible, so "is ``attr`` guarded in a
  base?" is one chain walk (the lock-discipline idiom, centralized).
* **call graph** — per-function outgoing edges resolved for the shapes
  that matter here: bare-name calls, ``self.method()`` through the
  hierarchy, ``self.attr.method()`` / ``local.method()`` through the
  attribute/local type maps, and ``module.fn()`` through imports.
  Resolution is deliberately best-effort: an unresolved call is simply
  absent (rules built on this follow ONE call-edge level, the jit-purity
  precedent, so a missing edge costs recall, never a false positive).
* **lock model** — per class: owned locks (``self.X = threading.Lock()``
  / ``RLock()``), condition aliases (``self.C =
  threading.Condition(self.X)`` acquires ``X``), and per-function
  acquisition sites (``with <lockref>:`` scopes plus ``# dklint:
  holds=<lock>`` pragmas declaring locks held at entry).  Lock IDENTITY
  resolves to the defining class in the hierarchy — a subclass's
  ``with self.mutex:`` and the base that created ``mutex`` name the
  same node, so the lock-order graph never splits one mutex into two.
* **attribute/local types** — ``self.a = ClassName(...)`` in any
  method, one constructor back-propagation pass (``KVFabric(self)``
  inside ``ServeRouter`` binds ``KVFabric.router -> ServeRouter`` when
  its ``__init__`` stores the parameter), and per-function locals bound
  by ``v = ClassName(...)`` / ``v = self.attr``.

Everything here is pure AST bookkeeping — no imports of scanned code.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileContext

_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock"}

#: containers whose in-place mutation needs a guard once shared
_MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def module_name_for(rel: str) -> str:
    """Anchored relative path -> dotted module name.
    ``a/b/c.py`` -> ``a.b.c``; ``a/b/__init__.py`` -> ``a.b``;
    a bare ``foo.py`` (fixture sources) -> ``foo``."""
    rel = rel.replace("\\", "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    parts = [p for p in rel.split("/") if p]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "<module>"


class FuncInfo:
    """One function or method definition."""

    __slots__ = ("name", "qname", "node", "module", "cls",
                 "acquires", "calls")

    def __init__(self, name: str, qname: str, node: ast.AST,
                 module: "ModuleInfo", cls: Optional["ClassInfo"]):
        self.name = name
        self.qname = qname
        self.node = node
        self.module = module
        self.cls = cls
        #: direct lexical lock acquisitions: [(LockNode, ast node)]
        self.acquires: List[Tuple["LockNode", ast.AST]] = []
        #: resolved outgoing call edges: [(FuncInfo, call ast node)]
        self.calls: List[Tuple["FuncInfo", ast.Call]] = []


class ClassInfo:
    """One class definition with its resolved shape."""

    def __init__(self, name: str, qname: str, node: ast.ClassDef,
                 module: "ModuleInfo"):
        self.name = name
        self.qname = qname
        self.node = node
        self.module = module
        self.base_names: List[str] = [
            b for b in (_dotted(x) for x in node.bases) if b]
        self.bases: List["ClassInfo"] = []       # resolved project bases
        self.methods: Dict[str, FuncInfo] = {}
        #: lock attr -> "Lock" | "RLock"
        self.locks: Dict[str, str] = {}
        #: condition/alias attr -> underlying lock attr
        self.lock_aliases: Dict[str, str] = {}
        #: self.attr -> ClassInfo (constructor-typed attributes)
        self.attr_types: Dict[str, "ClassInfo"] = {}
        #: attrs holding bare mutable containers assigned in __init__
        self.mutable_attrs: Set[str] = set()

    def mro_chain(self, _depth: int = 0) -> List["ClassInfo"]:
        """self + resolved project bases, nearest first (bounded)."""
        chain = [self]
        if _depth < 8:
            for b in self.bases:
                for c in b.mro_chain(_depth + 1):
                    if c not in chain:
                        chain.append(c)
        return chain

    def find_method(self, name: str) -> Optional[FuncInfo]:
        for c in self.mro_chain():
            m = c.methods.get(name)
            if m is not None:
                return m
        return None

    def lock_kind(self, attr: str) -> Optional[str]:
        """``Lock``/``RLock`` for ``attr`` (aliases followed) anywhere in
        the hierarchy, else None."""
        node = self.resolve_lock(attr)
        if node is None:
            return None
        return node.kind

    def resolve_lock(self, attr: str) -> Optional["LockNode"]:
        """Lock node for ``self.<attr>`` as seen from this class: the
        DEFINING class in the hierarchy owns the identity; condition
        aliases resolve to their underlying lock."""
        for c in self.mro_chain():
            under = c.lock_aliases.get(attr)
            if under is not None:
                return self.resolve_lock(under)
            if attr in c.locks:
                return LockNode(c, attr, c.locks[attr])
        return None

    def has_any_lock(self) -> bool:
        return any(c.locks for c in self.mro_chain())


class LockNode:
    """Identity of one lock: (defining class, attribute)."""

    __slots__ = ("cls", "attr", "kind")

    def __init__(self, cls: ClassInfo, attr: str, kind: str):
        self.cls = cls
        self.attr = attr
        self.kind = kind  # "Lock" | "RLock"

    @property
    def id(self) -> str:
        return f"{self.cls.qname}.{self.attr}"

    @property
    def label(self) -> str:
        return f"{self.cls.name}.{self.attr}"

    def __eq__(self, other) -> bool:
        return isinstance(other, LockNode) and self.id == other.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __repr__(self) -> str:
        return f"LockNode({self.id})"


class ModuleInfo:
    """One scanned file: import table + top-level defs."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.name = module_name_for(ctx.rel)
        #: local name -> dotted absolute target (module or symbol)
        self.imports: Dict[str, str] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self._scan_imports()

    # -- imports ------------------------------------------------------------
    def _package_parts(self) -> List[str]:
        parts = self.name.split(".")
        rel = self.ctx.rel.replace("\\", "/")
        if rel.endswith("/__init__.py"):
            return parts          # a package imports relative to itself
        return parts[:-1]

    def _scan_imports(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    self.imports[local] = target
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self._package_parts()
                    if node.level > 1:
                        base = base[:-(node.level - 1)] or base
                    prefix = ".".join(base)
                    mod = f"{prefix}.{node.module}" if node.module \
                        else prefix
                else:
                    mod = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.imports[local] = f"{mod}.{alias.name}"


class ProjectGraph:
    """The whole-repo structure: modules, classes, functions, call graph
    and the lock model.  Build with :func:`build_graph` (from paths) or
    directly from parsed ``FileContext``s."""

    def __init__(self, contexts: Sequence[FileContext]):
        self.modules: Dict[str, ModuleInfo] = {}
        self.contexts: List[FileContext] = list(contexts)
        for ctx in contexts:
            mod = ModuleInfo(ctx)
            self.modules[mod.name] = mod
        #: every FuncInfo in the project (iteration order = definition)
        self.functions: List[FuncInfo] = []
        self._collect_defs()
        self._resolve_bases()
        self._extract_class_shapes()
        self._backprop_ctor_params()
        self._resolve_calls_and_locks()

    # -- phase 1: definitions ----------------------------------------------
    def _collect_defs(self) -> None:
        for mod in self.modules.values():
            for node in mod.ctx.tree.body:
                self._collect_in(mod, node, None)

    def _collect_in(self, mod: ModuleInfo, node: ast.AST,
                    cls: Optional[ClassInfo]) -> None:
        if isinstance(node, ast.ClassDef):
            qname = f"{mod.name}.{node.name}"
            info = ClassInfo(node.name, qname, node, mod)
            mod.classes[node.name] = info
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    fn = FuncInfo(item.name, f"{qname}.{item.name}",
                                  item, mod, info)
                    info.methods[item.name] = fn
                    self.functions.append(fn)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = FuncInfo(node.name, f"{mod.name}.{node.name}",
                          node, mod, None)
            mod.functions[node.name] = fn
            self.functions.append(fn)

    # -- phase 2: class hierarchy -------------------------------------------
    def resolve_class(self, mod: ModuleInfo,
                      dotted: Optional[str]) -> Optional[ClassInfo]:
        """Resolve a dotted name as seen from ``mod`` to a project
        class: local class, imported symbol, or ``alias.Class`` through
        an imported module."""
        if not dotted:
            return None
        parts = dotted.split(".")
        if len(parts) == 1:
            cls = mod.classes.get(parts[0])
            if cls is not None:
                return cls
            target = mod.imports.get(parts[0])
            if target is not None:
                return self._class_by_qname(target)
            return None
        head = mod.imports.get(parts[0])
        if head is not None:
            return self._class_by_qname(".".join([head] + parts[1:]))
        return self._class_by_qname(dotted)

    def _class_by_qname(self, qname: str) -> Optional[ClassInfo]:
        mod_name, _, cls_name = qname.rpartition(".")
        m = self.modules.get(mod_name)
        if m is not None and cls_name in m.classes:
            return m.classes[cls_name]
        # symbol re-exported through a package __init__: follow one hop
        m = self.modules.get(qname.rpartition(".")[0])
        if m is None:
            m = self.modules.get(qname)
        if m is not None:
            target = m.imports.get(cls_name) if cls_name else None
            if target is not None and target != qname:
                return self._class_by_qname(target)
        return None

    def resolve_function(self, mod: ModuleInfo,
                         dotted: Optional[str]) -> Optional[FuncInfo]:
        """Bare or dotted callable as seen from ``mod`` -> FuncInfo (a
        class name resolves to its ``__init__``)."""
        if not dotted:
            return None
        parts = dotted.split(".")
        if len(parts) == 1:
            fn = mod.functions.get(parts[0])
            if fn is not None:
                return fn
            cls = mod.classes.get(parts[0])
            if cls is not None:
                return cls.find_method("__init__")
            target = mod.imports.get(parts[0])
            if target is not None:
                return self._func_by_qname(target)
            return None
        head = mod.imports.get(parts[0])
        if head is not None:
            return self._func_by_qname(".".join([head] + parts[1:]))
        return self._func_by_qname(dotted)

    def _func_by_qname(self, qname: str) -> Optional[FuncInfo]:
        mod_name, _, fn_name = qname.rpartition(".")
        m = self.modules.get(mod_name)
        if m is not None:
            if fn_name in m.functions:
                return m.functions[fn_name]
            if fn_name in m.classes:
                return m.classes[fn_name].find_method("__init__")
            target = m.imports.get(fn_name)
            if target is not None and target != qname:
                return self._func_by_qname(target)
        cls = self._class_by_qname(qname)
        if cls is not None:
            return cls.find_method("__init__")
        return None

    def _resolve_bases(self) -> None:
        for mod in self.modules.values():
            for cls in mod.classes.values():
                for b in cls.base_names:
                    base = self.resolve_class(mod, b)
                    if base is not None and base is not cls:
                        cls.bases.append(base)

    # -- phase 3: lock model + attribute types ------------------------------
    def _extract_class_shapes(self) -> None:
        for mod in self.modules.values():
            for cls in mod.classes.values():
                for name, fn in cls.methods.items():
                    self._scan_method_assigns(mod, cls, fn,
                                              in_init=(name == "__init__"))

    def _scan_method_assigns(self, mod: ModuleInfo, cls: ClassInfo,
                             fn: FuncInfo, in_init: bool) -> None:
        # parameter annotations type the attrs they're stored into:
        # ``def __init__(self, ps: ParameterServer): self.ps = ps``
        ann_params: Dict[str, ClassInfo] = {}
        for a in getattr(fn.node.args, "args", [])[1:]:
            if a.annotation is not None:
                t = self.resolve_class(mod, _dotted(a.annotation))
                if t is not None:
                    ann_params[a.arg] = t
        for node in ast.walk(fn.node):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            attrs = [a for a in (_self_attr(t) for t in targets) if a]
            if attrs and isinstance(node, ast.AnnAssign) and \
                    node.annotation is not None:
                t = self.resolve_class(mod, _dotted(node.annotation))
                if t is not None:
                    for attr in attrs:
                        cls.attr_types.setdefault(attr, t)
            if attrs and isinstance(value, ast.Name) and \
                    value.id in ann_params:
                for attr in attrs:
                    cls.attr_types.setdefault(attr, ann_params[value.id])
            if not attrs or not isinstance(value, ast.Call):
                if attrs and in_init and isinstance(
                        value, (ast.Dict, ast.List, ast.Set,
                                ast.DictComp, ast.ListComp, ast.SetComp)):
                    cls.mutable_attrs.update(attrs)
                continue
            term = _terminal(value.func)
            for attr in attrs:
                if term in _LOCK_CTORS:
                    cls.locks[attr] = _LOCK_CTORS[term]
                elif term == "Condition":
                    under = _self_attr(value.args[0]) if value.args \
                        else None
                    if under:
                        cls.lock_aliases[attr] = under
                    else:
                        # a Condition() owns a fresh internal lock
                        cls.locks[attr] = "RLock"
                elif term in _MUTABLE_CTORS and in_init:
                    cls.mutable_attrs.add(attr)
                else:
                    target = self.resolve_class(mod,
                                                _dotted(value.func))
                    if target is not None:
                        cls.attr_types[attr] = target

    def _backprop_ctor_params(self) -> None:
        """One pass of constructor-parameter typing: a call
        ``K(self, ...)`` inside class C binds K.__init__'s first real
        parameter to C; ``self.p = that_param`` in K.__init__ then types
        ``K.p`` — how ``KVFabric(router)`` learns its ``.router``."""
        for fn in self.functions:
            local_types = self._local_types(fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee_cls = self.resolve_class(fn.module,
                                                _dotted(node.func))
                if callee_cls is None:
                    continue
                init = callee_cls.find_method("__init__")
                if init is None or init.cls is None:
                    continue
                params = [a.arg for a in init.node.args.args[1:]]
                bindings: List[Tuple[str, ast.AST]] = list(
                    zip(params, node.args))
                bindings.extend((kw.arg, kw.value)
                                for kw in node.keywords
                                if kw.arg in params)
                for pname, arg in bindings:
                    bound: Optional[ClassInfo] = None
                    if isinstance(arg, ast.Name):
                        if arg.id == "self" and fn.cls is not None:
                            bound = fn.cls
                        else:
                            bound = local_types.get(arg.id)
                    attr = _self_attr(arg)
                    if attr is not None and fn.cls is not None:
                        bound = fn.cls.attr_types.get(attr)
                    if bound is None:
                        continue
                    for sub in ast.walk(init.node):
                        if isinstance(sub, ast.Assign) and \
                                isinstance(sub.value, ast.Name) and \
                                sub.value.id == pname:
                            for t in sub.targets:
                                a = _self_attr(t)
                                if a and a not in init.cls.attr_types:
                                    init.cls.attr_types[a] = bound

    # -- phase 4: calls + acquisitions --------------------------------------
    def _local_types(self, fn: FuncInfo) -> Dict[str, ClassInfo]:
        """Var -> class for simple local bindings inside ``fn``:
        ``v = ClassName(...)`` and ``v = self.attr`` (typed attrs)."""
        out: Dict[str, ClassInfo] = {}
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign) or \
                    len(node.targets) != 1 or \
                    not isinstance(node.targets[0], ast.Name):
                continue
            var = node.targets[0].id
            if isinstance(node.value, ast.Call):
                cls = self.resolve_class(fn.module,
                                         _dotted(node.value.func))
                if cls is not None:
                    out[var] = cls
            else:
                attr = _self_attr(node.value)
                if attr and fn.cls is not None:
                    t = fn.cls.attr_types.get(attr)
                    if t is not None:
                        out[var] = t
        return out

    def receiver_class(self, fn: FuncInfo, node: ast.AST,
                       local_types: Dict[str, ClassInfo]
                       ) -> Optional[ClassInfo]:
        """Best-effort type of an expression used as a receiver:
        ``self`` / ``self.attr`` / local var / local var's attr."""
        if isinstance(node, ast.Name):
            if node.id == "self" and fn.cls is not None:
                return fn.cls
            return local_types.get(node.id)
        if isinstance(node, ast.Attribute):
            owner = self.receiver_class(fn, node.value, local_types)
            if owner is not None:
                for c in owner.mro_chain():
                    t = c.attr_types.get(node.attr)
                    if t is not None:
                        return t
        return None

    def resolve_lock_ref(self, fn: FuncInfo, expr: ast.AST,
                         local_types: Dict[str, ClassInfo]
                         ) -> Optional[LockNode]:
        """``with <expr>:`` -> the lock node it acquires, when ``expr``
        is ``self.X`` / ``<typed receiver>.X`` and ``X`` is a lock (or
        condition alias) of the receiver's class hierarchy."""
        if not isinstance(expr, ast.Attribute):
            return None
        owner = self.receiver_class(fn, expr.value, local_types)
        if owner is None:
            return None
        return owner.resolve_lock(expr.attr)

    def _resolve_calls_and_locks(self) -> None:
        for fn in self.functions:
            local_types = self._local_types(fn)
            seen_locks: Set[str] = set()
            for node in ast.walk(fn.node):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        lock = self.resolve_lock_ref(
                            fn, item.context_expr, local_types)
                        if lock is not None:
                            fn.acquires.append((lock, item.context_expr))
                            seen_locks.add(lock.id)
                elif isinstance(node, ast.Call):
                    callee = self._resolve_call(fn, node, local_types)
                    if callee is not None and callee is not fn:
                        fn.calls.append((callee, node))

    def _resolve_call(self, fn: FuncInfo, node: ast.Call,
                      local_types: Dict[str, ClassInfo]
                      ) -> Optional[FuncInfo]:
        func = node.func
        if isinstance(func, ast.Name):
            return self.resolve_function(fn.module, func.id)
        if isinstance(func, ast.Attribute):
            owner = self.receiver_class(fn, func.value, local_types)
            if owner is not None:
                return owner.find_method(func.attr)
            dotted = _dotted(func)
            if dotted is not None:
                return self.resolve_function(fn.module, dotted)
        return None

    # -- holds pragmas ------------------------------------------------------
    def held_at_entry(self, fn: FuncInfo) -> List[LockNode]:
        """Locks a ``# dklint: holds=`` pragma declares held when ``fn``
        is entered, resolved in the owning class's hierarchy (a subclass
        method may declare a base-class lock)."""
        if fn.cls is None:
            return []
        names = fn.module.ctx.holds(fn.node.lineno)
        out = []
        for n in sorted(names):
            lock = fn.cls.resolve_lock(n)
            if lock is not None:
                out.append(lock)
        return out


def build_graph(contexts: Iterable[FileContext]) -> ProjectGraph:
    """The one entry point rules use."""
    return ProjectGraph(list(contexts))

"""dklint — static analysis + runtime race checking for this stack
(ISSUE 3 tentpole).

An asynchronous parameter-server stack is exactly the shape of code where
Python-side hazards corrupt training without failing a test: a
``time.time()`` traced into a jit program is one frozen constant, an
instance attribute written outside the mutex is a silent lost update, a
bare ``except:`` turns a wire error into NaN weights three epochs later.
PR 2 proved the pattern with a one-off AST gate for ``print(``; this
package generalizes it:

* ``core``      — rule/finding framework, inline-pragma + baseline
  suppression, the ``run_paths`` driver.
* ``rules``     — the repo-specific rule set (jit-purity,
  lock-discipline, swallow-guard, thread-shutdown, bare-print).
* ``racecheck`` — opt-in runtime proxies (tracked locks + guarded dicts)
  that fail threaded tests on unguarded shared-state writes.
* ``cli``       — the ``dklint`` console entry point
  (``scripts/dklint.py`` wraps it).

The tier-1 gate (``tests/test_analysis.py::test_repo_is_dklint_clean``)
runs the full rule set over ``distkeras_tpu/`` — any new finding fails
the build unless deliberately suppressed.
"""

from .core import (  # noqa: F401
    FileContext, Finding, Report, Rule, analyze_source, apply_baseline,
    iter_py_files, load_baseline, run_paths, write_baseline)
from .rules import ALL_RULES, RULES_BY_ID  # noqa: F401

"""dklint core — the analysis driver the rules plug into.

A ``Rule`` inspects one parsed file (``FileContext``: source + AST +
comment pragmas) and returns ``Finding``s.  The driver (``analyze_source``
/ ``run_paths``) applies suppression in two layers:

* **inline pragmas** — ``# dklint: disable=rule-a,rule-b`` (or a bare
  ``# dklint: disable``) on the offending line silences that line; a
  ``# dklint: holds=mutex`` pragma on a ``def`` line declares a lock
  contract ("callers hold ``self.mutex``") that the lock-discipline rule
  honors — suppression that *documents* instead of hiding.
* **baseline file** — a committed JSON set of finding fingerprints
  (``write_baseline`` / ``load_baseline``): pre-existing debt stays
  visible in the file but does not fail the gate, while any NEW finding
  does.  Fingerprints hash the rule id + file-relative path + the
  offending source line (plus an occurrence index), not line numbers, so
  unrelated edits above a suppressed finding don't invalidate it.

Findings are plain dataclasses (``as_dict`` is JSON-safe) so the CLI's
``--format json`` and the tests consume the same objects.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: pragma grammar: ``# dklint: disable=a,b`` / ``# dklint: disable`` /
#: ``# dklint: holds=mutex`` — anywhere in a line's trailing comment
_PRAGMA = re.compile(r"#\s*dklint:\s*(disable|holds)\s*(?:=\s*([\w.,\- ]+))?")


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str          # path as scanned (display)
    rel: str           # path relative to the scan root (stable fingerprints)
    line: int
    col: int
    message: str
    snippet: str       # the offending source line, stripped
    fingerprint: str = ""   # assigned by the driver (baseline identity)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}"


class FileContext:
    """One parsed file handed to every rule: source, AST, line table and
    the ``# dklint:`` pragmas keyed by line number."""

    def __init__(self, path: str, source: str, rel: Optional[str] = None):
        self.path = path
        self.rel = rel if rel is not None else os.path.basename(path)
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self._disable: Dict[int, Optional[Set[str]]] = {}
        self._holds: Dict[int, Set[str]] = {}
        for lineno, text in enumerate(self.lines, start=1):
            for kind, arg in _PRAGMA.findall(text):
                names = {a.strip() for a in (arg or "").split(",") if a.strip()}
                if kind == "disable":
                    # None = every rule disabled on this line
                    self._disable[lineno] = names or None
                else:
                    self._holds.setdefault(lineno, set()).update(names)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def disabled(self, lineno: int, rule: str) -> bool:
        if lineno not in self._disable:
            return False
        names = self._disable[lineno]
        return names is None or rule in names

    def holds(self, lineno: int) -> Set[str]:
        """Lock names a ``# dklint: holds=...`` pragma declares held for
        the scope opened at ``lineno`` (normally a ``def`` line)."""
        names = self._holds.get(lineno, set())
        return {n.split(".")[-1] for n in names}  # accept self.mutex / mutex


class Rule:
    """Base rule: subclasses set ``id``/``description`` and implement
    ``check(ctx) -> [Finding]``."""

    id: str = ""
    description: str = ""

    def check(self, ctx: FileContext) -> List[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=self.id, path=ctx.path, rel=ctx.rel,
                       line=line, col=getattr(node, "col_offset", 0),
                       message=message, snippet=ctx.source_line(line))


@dataclasses.dataclass
class Report:
    """Driver output: active findings plus everything suppressed (kept so
    the CLI can show honest counts) and any files that failed to parse."""

    findings: List[Finding] = dataclasses.field(default_factory=list)
    inline_suppressed: List[Finding] = dataclasses.field(default_factory=list)
    baseline_suppressed: List[Finding] = dataclasses.field(default_factory=list)
    errors: List[Tuple[str, str]] = dataclasses.field(default_factory=list)


#: markers that anchor stable finding paths (and baseline discovery): the
#: nearest ancestor directory holding one of these is "the repo root"
ANCHOR_MARKERS = ("dklint_baseline.json", "pyproject.toml", ".git")


def find_anchor(start: str) -> Optional[str]:
    """Nearest ancestor of ``start`` containing an anchor marker."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if any(os.path.exists(os.path.join(cur, m)) for m in ANCHOR_MARKERS):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def anchor_base(root: str) -> str:
    """The directory finding paths are made relative to, resolved ONCE
    per scan root: the root's anchor (see ``find_anchor``), else the root
    itself — so ``dklint distkeras_tpu/``, ``dklint .`` and
    ``dklint distkeras_tpu/ps/servers.py`` all fingerprint a finding as
    ``distkeras_tpu/ps/servers.py`` and the baseline keeps matching."""
    base = find_anchor(root)
    if base is None:
        base = os.path.abspath(root)
        if os.path.isfile(base):
            base = os.path.dirname(base)
    return base


def iter_py_files(path: str) -> Iterable[Tuple[str, str]]:
    """Yield ``(full_path, anchored_rel_path)`` for every ``.py`` under
    ``path`` (or ``path`` itself), skipping caches/hidden directories.
    The anchor lookup happens once for the whole walk — every file under
    one root shares it."""
    base = anchor_base(path)
    if os.path.isfile(path):
        yield path, os.path.relpath(os.path.abspath(path),
                                    base).replace(os.sep, "/")
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and not d.startswith("."))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                yield full, os.path.relpath(os.path.abspath(full),
                                            base).replace(os.sep, "/")


def _split_rules(rules: Optional[Sequence[Rule]]
                 ) -> Tuple[List[Rule], List[Rule]]:
    """(per-file rules, project rules).  Project rules (``project=True``,
    see ``rules_project.ProjectRule``) run ONCE over the whole scan's
    graph instead of per file."""
    from .rules import ALL_RULES
    all_rules = list(rules if rules is not None else ALL_RULES)
    file_rules = [r for r in all_rules if not getattr(r, "project", False)]
    project_rules = [r for r in all_rules if getattr(r, "project", False)]
    return file_rules, project_rules


def _check_project(contexts: Sequence["FileContext"],
                   project_rules: Sequence[Rule],
                   report: "Report") -> None:
    """Run the interprocedural rules over the graph of every parsed
    file; inline ``# dklint: disable`` pragmas still apply, keyed by the
    file each finding anchors in (findings in non-Python files — e.g.
    OBS_BASELINE.json — have no pragma channel and pass through)."""
    if not project_rules or not contexts:
        return
    from .graph import build_graph
    graph = build_graph(contexts)
    ctx_by_rel = {c.rel: c for c in contexts}
    for rule in project_rules:
        for f in rule.check_project(graph):
            ctx = ctx_by_rel.get(f.rel)
            if ctx is not None and ctx.disabled(f.line, f.rule):
                report.inline_suppressed.append(f)
            else:
                report.findings.append(f)


def analyze_source(source: str, path: str = "<string>",
                   rel: Optional[str] = None,
                   rules: Optional[Sequence[Rule]] = None,
                   _finalize: bool = True) -> Report:
    """Run ``rules`` over one source string; inline pragmas applied.
    Project rules see a single-file graph (fixture tests exercise the
    interprocedural rules through the same door).  ``_finalize=False``
    skips the sort + fingerprint pass (``run_paths`` does both once over
    the aggregate instead)."""
    file_rules, project_rules = _split_rules(rules)
    report = Report()
    try:
        ctx = FileContext(path, source, rel=rel)
    except SyntaxError as e:
        report.errors.append((path, f"syntax error: {e}"))
        return report
    for rule in file_rules:
        for f in rule.check(ctx):
            if ctx.disabled(f.line, f.rule):
                report.inline_suppressed.append(f)
            else:
                report.findings.append(f)
    _check_project([ctx], project_rules, report)
    if _finalize:
        report.findings.sort(key=lambda f: (f.rel, f.line, f.col, f.rule))
        _assign_fingerprints(report.findings)
    return report


def run_paths(paths: Sequence[str],
              rules: Optional[Sequence[Rule]] = None,
              jobs: int = 1) -> Report:
    """Run ``rules`` over files/directories; findings carry fingerprints
    relative to each scan root so the baseline survives repo moves.
    ``jobs > 1`` parses and per-file-checks files on a thread pool (the
    interprocedural pass still runs once, over every parsed file);
    output is deterministic either way — merge order is the sorted walk
    order, not completion order."""
    file_rules, project_rules = _split_rules(rules)
    report = Report()
    work: List[Tuple[str, str]] = []
    for root in paths:
        if not os.path.exists(root):
            report.errors.append((root, "no such file or directory"))
            continue
        work.extend(iter_py_files(root))

    def _one(item: Tuple[str, str]):
        full, rel = item
        try:
            with open(full, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            return None, [(full, f"unreadable: {e}")], [], []
        try:
            ctx = FileContext(full, source, rel=rel)
        except SyntaxError as e:
            return None, [(full, f"syntax error: {e}")], [], []
        found, suppressed = [], []
        for rule in file_rules:
            for f in rule.check(ctx):
                (suppressed if ctx.disabled(f.line, f.rule)
                 else found).append(f)
        return ctx, [], found, suppressed

    if jobs > 1 and len(work) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=jobs) as pool:
            results = list(pool.map(_one, work))
    else:
        results = [_one(item) for item in work]

    contexts: List[FileContext] = []
    for ctx, errors, found, suppressed in results:
        if ctx is not None:
            contexts.append(ctx)
        report.errors.extend(errors)
        report.findings.extend(found)
        report.inline_suppressed.extend(suppressed)
    _check_project(contexts, project_rules, report)
    report.findings.sort(key=lambda f: (f.rel, f.line, f.col, f.rule))
    _assign_fingerprints(report.findings)
    return report


def _assign_fingerprints(findings: List[Finding]) -> None:
    """Line-number-independent identity: hash of (rule, rel path, stripped
    source line, k-th occurrence of that triple in the file)."""
    seen: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        key = (f.rule, f.rel, f.snippet)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        payload = "\x00".join([f.rule, f.rel, f.snippet, str(idx)])
        f.fingerprint = hashlib.sha1(payload.encode()).hexdigest()[:16]


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> Set[str]:
    """Baseline file -> set of suppressed fingerprints."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "findings" not in doc:
        raise ValueError(f"{path}: not a dklint baseline "
                         f"(expected a mapping with a 'findings' list)")
    return {entry["fingerprint"] for entry in doc["findings"]}


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    """Persist ``findings`` as the accepted-debt baseline (sorted, with
    location context so the file reviews like code)."""
    doc = {
        "version": 1,
        "note": "accepted pre-existing dklint findings; regenerate with "
                "`dklint --write-baseline` after deliberate changes",
        "findings": [
            {"fingerprint": f.fingerprint, "rule": f.rule, "path": f.rel,
             "message": f.message, "snippet": f.snippet}
            for f in sorted(findings, key=lambda f: (f.rel, f.line, f.col))
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")


def apply_baseline(report: Report, fingerprints: Set[str]) -> Report:
    """Move baseline-matched findings out of the active list (in place)."""
    active, suppressed = [], []
    for f in report.findings:
        (suppressed if f.fingerprint in fingerprints else active).append(f)
    report.findings = active
    report.baseline_suppressed.extend(suppressed)
    return report

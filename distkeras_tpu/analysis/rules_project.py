"""dklint project rules (ISSUE 18) — the rules that need the whole repo.

Per-file rules (``rules.py``) see one AST; these see the
``graph.ProjectGraph`` built over every scanned file and reason across
call, inheritance and configuration edges:

* ``lock-order-cycle`` — the static lock-acquisition-order graph.  An
  edge A→B means some code path takes lock B while holding A (lexical
  ``with`` nesting, ``# dklint: holds=`` entry contracts, and ONE
  call-edge level — the jit-purity precedent).  A cycle is a potential
  deadlock: two threads entering the cycle from different nodes can each
  hold the lock the other needs.  Nested acquisition of a non-reentrant
  ``Lock`` the thread already holds is reported directly (a guaranteed
  self-deadlock); ``RLock`` re-entry is legal and never an edge.
* ``metric-contract`` — cross-checks the three places a metric name
  lives: creation sites in code (``registry.counter/gauge/histogram``
  literals and f-strings, span names), the drift-gate config
  (``OBS_BASELINE.json`` per-metric thresholds / ignore list /
  snapshot files) and the ``scripts/obsview.py`` renderers.  A
  threshold that matches no creation site gates nothing; a renderer
  read nobody emits renders a permanent blank; an exactly-gated counter
  created on first use violates the "0 is present, not missing"
  invariant the drift gate depends on (a missing metric is skipped, a
  present 0 is compared).
* ``handoff-protocol`` — the static analogue of racecheck's
  write-lockset check: handing an object that carries bare mutable
  containers and owns no lock to another thread (``Thread(args=...)``,
  ``queue.put``, callback/hook registration) publishes unguarded state.

All three follow dklint's precedent: conservative resolution, so an
edge we cannot prove is silence (recall cost), never a false positive.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Rule
from .graph import FuncInfo, LockNode, ProjectGraph


class ProjectRule(Rule):
    """A rule that runs once over the whole scan (``check_project``)
    instead of per file.  ``check`` is a no-op so mixed rule lists keep
    working everywhere a plain ``Rule`` is accepted."""

    project = True

    def check(self, ctx) -> List[Finding]:
        return []

    def check_project(self, graph: ProjectGraph) -> List[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# lock-order-cycle
# ---------------------------------------------------------------------------

class _Edge:
    """First witness for one lock-order edge A -> B."""

    __slots__ = ("src", "dst", "ctx", "node", "how")

    def __init__(self, src: LockNode, dst: LockNode, ctx, node, how: str):
        self.src = src
        self.dst = dst
        self.ctx = ctx
        self.node = node
        self.how = how  # human description of the acquisition


class LockOrderCycleRule(ProjectRule):
    id = "lock-order-cycle"
    description = ("static lock-acquisition-order graph over the whole "
                   "repo; cycles are potential deadlocks, nested "
                   "re-acquisition of a non-reentrant Lock is a "
                   "guaranteed one")

    _MAX_CYCLE = 6

    def check_project(self, graph: ProjectGraph) -> List[Finding]:
        findings: List[Finding] = []
        edges: Dict[Tuple[str, str], _Edge] = {}
        for fn in graph.functions:
            self._walk_function(graph, fn, edges, findings)
        findings.extend(self._cycle_findings(edges))
        return findings

    # -- per-function lexical walk ------------------------------------------
    def _walk_function(self, graph: ProjectGraph, fn: FuncInfo,
                       edges: Dict[Tuple[str, str], _Edge],
                       findings: List[Finding]) -> None:
        local_types = graph._local_types(fn)
        held = list(graph.held_at_entry(fn))
        body = getattr(fn.node, "body", [])
        self._walk_block(graph, fn, body, held, local_types,
                         edges, findings)

    def _walk_block(self, graph, fn, stmts, held, local_types,
                    edges, findings) -> None:
        for stmt in stmts:
            self._walk_stmt(graph, fn, stmt, held, local_types,
                            edges, findings)

    def _walk_stmt(self, graph, fn, stmt, held, local_types,
                   edges, findings) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # a nested def runs later, not under this held set
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: List[LockNode] = []
            for item in stmt.items:
                lock = graph.resolve_lock_ref(fn, item.context_expr,
                                              local_types)
                if lock is None:
                    self._scan_calls(graph, fn, item.context_expr, held,
                                     local_types, edges)
                    continue
                for h in held:
                    if h.id == lock.id:
                        if lock.kind == "Lock":
                            findings.append(self.finding(
                                fn.module.ctx, item.context_expr,
                                f"self-deadlock: {fn.qname} re-acquires "
                                f"non-reentrant lock {lock.label} it "
                                f"already holds (make it an RLock or "
                                f"hoist the outer acquisition)"))
                    else:
                        self._edge(edges, h, lock, fn.module.ctx,
                                   item.context_expr,
                                   f"{fn.qname} takes {lock.label} in a "
                                   f"`with` while holding {h.label}")
                acquired.append(lock)
            self._walk_block(graph, fn, stmt.body,
                             held + acquired, local_types,
                             edges, findings)
            return
        # other statements: recurse into child statement blocks, scan
        # the expression parts for calls made while locks are held
        for field, value in ast.iter_fields(stmt):
            if isinstance(value, list) and value and \
                    isinstance(value[0], ast.stmt):
                self._walk_block(graph, fn, value, held, local_types,
                                 edges, findings)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.excepthandler):
                        self._walk_block(graph, fn, v.body, held,
                                         local_types, edges, findings)
                    elif isinstance(v, ast.AST):
                        self._scan_calls(graph, fn, v, held,
                                         local_types, edges)
            elif isinstance(value, ast.AST):
                self._scan_calls(graph, fn, value, held, local_types,
                                 edges)

    def _scan_calls(self, graph, fn, expr, held, local_types,
                    edges) -> None:
        """ONE call-edge level: while holding ``held``, a resolved
        callee's own direct acquisitions become order edges (witnessed
        at the call site).  Lambda bodies run later — skipped."""
        if not held:
            return
        for node in self._walk_no_lambda(expr):
            if not isinstance(node, ast.Call):
                continue
            callee = graph._resolve_call(fn, node, local_types)
            if callee is None or callee is fn:
                continue
            for lock, _ in callee.acquires:
                for h in held:
                    if h.id == lock.id:
                        continue  # re-entry handled by callee's own walk
                    self._edge(edges, h, lock, fn.module.ctx, node,
                               f"{fn.qname} calls {callee.qname} "
                               f"(which takes {lock.label}) while "
                               f"holding {h.label}")

    @staticmethod
    def _walk_no_lambda(root):
        stack = [root]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, ast.Lambda):
                    stack.append(child)

    @staticmethod
    def _edge(edges, src: LockNode, dst: LockNode, ctx, node,
              how: str) -> None:
        key = (src.id, dst.id)
        if key not in edges:
            edges[key] = _Edge(src, dst, ctx, node, how)

    # -- cycles -------------------------------------------------------------
    def _cycle_findings(self, edges: Dict[Tuple[str, str], _Edge]
                        ) -> List[Finding]:
        adj: Dict[str, List[str]] = {}
        for (u, v) in edges:
            adj.setdefault(u, []).append(v)
        for vs in adj.values():
            vs.sort()
        cycles: List[Tuple[str, ...]] = []

        def dfs(start: str, node: str, path: List[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start and len(path) >= 2:
                    cycles.append(tuple(path))
                elif nxt > start and nxt not in path and \
                        len(path) < self._MAX_CYCLE:
                    dfs(start, nxt, path + [nxt])

        # each cycle enumerated exactly once: rooted at its smallest node
        for start in sorted(adj):
            dfs(start, start, [start])

        findings = []
        for cyc in sorted(cycles):
            witnesses = []
            for i, u in enumerate(cyc):
                v = cyc[(i + 1) % len(cyc)]
                e = edges[(u, v)]
                witnesses.append(e)
            label = " -> ".join([edges[(cyc[0], cyc[1])].src.label] +
                                [w.dst.label for w in witnesses])
            detail = "; ".join(
                f"{w.how} at {w.ctx.rel}:{w.node.lineno}"
                for w in witnesses)
            first = witnesses[0]
            findings.append(self.finding(
                first.ctx, first.node,
                f"potential deadlock: lock-order cycle {label} "
                f"({detail}) — pick one acquisition order and hoist or "
                f"drop the inner lock"))
        return findings


# ---------------------------------------------------------------------------
# metric-contract
# ---------------------------------------------------------------------------

#: instrument factories on a registry (or bare constructors)
_CREATE_METHODS = {"counter", "gauge", "histogram"}
_CREATE_CTORS = {"Counter", "Gauge", "Histogram"}
#: span factories — span names render next to metrics in obsview
_SPAN_METHODS = {"span", "_span"}
#: chained-use methods: ``registry.counter("x").inc()`` creates on first
#: use — exactly the shape the present-0 contract forbids on gated names
_USE_METHODS = {"inc", "add", "dec", "set", "observe"}

_METRIC_NAME = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_*]+)+$")


class _Site:
    __slots__ = ("rel", "line", "snippet", "chained", "is_glob", "kind")

    def __init__(self, rel, line, snippet, chained, is_glob, kind):
        self.rel = rel
        self.line = line
        self.snippet = snippet
        self.chained = chained
        self.is_glob = is_glob
        self.kind = kind  # "counter" | "gauge" | "histogram" | "span"


@lru_cache(maxsize=4096)
def _globs_intersect(a: str, b: str) -> bool:
    """Whether two ``*``-wildcard patterns share any concrete string."""
    if not a and not b:
        return True
    if a.startswith("*"):
        return _globs_intersect(a[1:], b) or \
            (bool(b) and _globs_intersect(a, b[1:]))
    if b.startswith("*"):
        return _globs_intersect(a, b[1:]) or \
            (bool(a) and _globs_intersect(a[1:], b))
    return bool(a) and bool(b) and a[0] == b[0] and \
        _globs_intersect(a[1:], b[1:])


def _lcs_len(a: str, b: str) -> int:
    """Longest common substring length (tiny inputs; O(len*len))."""
    best = 0
    prev = [0] * (len(b) + 1)
    for ca in a:
        cur = [0] * (len(b) + 1)
        for j, cb in enumerate(b, start=1):
            if ca == cb:
                cur[j] = prev[j - 1] + 1
                best = max(best, cur[j])
        prev = cur
    return best


def _pattern_matches_site(pattern: str, site_name: str,
                          site_glob: bool) -> bool:
    if not site_glob:
        return fnmatch.fnmatchcase(site_name, pattern)
    if not _globs_intersect(pattern, site_name):
        return False
    if "*" not in pattern:
        return True
    # glob vs glob: pure intersection is weak evidence (any open-ended
    # f-string creation "intersects" any suffix pattern) — additionally
    # require a shared literal fragment, so `*pull_cache_hits` is
    # matched by `*.pull_cache_hits` but not by `continual.verdicts_*`
    return _lcs_len(pattern.replace("*", "\x00"),
                    site_name.replace("*", "\x01")) >= 4


class MetricContractRule(ProjectRule):
    id = "metric-contract"
    description = ("every metric name must agree across creation sites, "
                   "OBS_BASELINE.json thresholds, alert rules and obsview "
                   "renderers; exactly-gated counters must be pre-created "
                   "(0 is present, not missing)")

    #: sources scanned for creation sites IN ADDITION to the lint paths.
    #: The package itself is listed so a partial scan (``--changed``, a
    #: subdirectory) still sees every creation site — otherwise metrics
    #: created outside the scanned subset would all read as "dead".
    _AUX = ("distkeras_tpu", "bench.py", "scripts")

    def check_project(self, graph: ProjectGraph) -> List[Finding]:
        root = self._repo_root(graph)
        if root is None:
            return []
        baseline_path = os.path.join(root, "OBS_BASELINE.json")
        if not os.path.isfile(baseline_path):
            return []
        try:
            with open(baseline_path, encoding="utf-8") as f:
                baseline = json.load(f)
            baseline_lines = open(baseline_path,
                                  encoding="utf-8").read().splitlines()
        except (OSError, json.JSONDecodeError):
            return []

        sites = self._creation_sites(graph, root)
        findings: List[Finding] = []
        self._check_baseline(root, baseline_path, baseline,
                             baseline_lines, sites, findings)
        self._check_alerts(root, baseline_path, baseline,
                           baseline_lines, sites, findings)
        self._check_obsview(root, sites, findings)
        self._check_precreated(baseline, sites, findings)
        return findings

    # -- plumbing -----------------------------------------------------------
    @staticmethod
    def _repo_root(graph: ProjectGraph) -> Optional[str]:
        from . import core
        for ctx in graph.contexts:
            if os.path.isfile(ctx.path):
                return core.find_anchor(ctx.path)
        return None

    def _creation_sites(self, graph: ProjectGraph,
                        root: str) -> Dict[str, List[_Site]]:
        """metric/span name (exact or ``*``-glob) -> creation sites,
        collected from the scanned graph plus the aux sources.

        A creation call carrying ``labels={...}`` (ISSUE 20) registers
        as the glob ``<name>.*`` — the instrument's FLAT name appends
        sorted ``<key><value>`` parts, so baseline patterns and obsview
        reads against the flattened family keep matching.  The literal
        label keys seen per base name land in ``self._labels_at`` for
        the alert-rule typo check."""
        sites: Dict[str, List[_Site]] = {}
        self._labels_at: Dict[str, Set[str]] = {}
        trees: List[Tuple[str, ast.AST]] = [
            (ctx.rel, ctx.tree) for ctx in graph.contexts]
        scanned = {c.rel for c in graph.contexts}
        for aux in self._AUX:
            full = os.path.join(root, aux)
            files = []
            if os.path.isfile(full):
                files = [full]
            elif os.path.isdir(full):
                for dirpath, dirnames, names in os.walk(full):
                    dirnames[:] = sorted(
                        d for d in dirnames
                        if not d.startswith(".") and d != "__pycache__")
                    files.extend(os.path.join(dirpath, f)
                                 for f in sorted(names)
                                 if f.endswith(".py"))
            for path in files:
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                if rel in scanned:
                    continue
                try:
                    with open(path, encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=path)
                except (OSError, SyntaxError):
                    continue
                trees.append((rel, tree))
        for rel, tree in trees:
            chained_ids = self._chained_creations(tree)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                func = node.func
                kind = None
                if isinstance(func, ast.Attribute):
                    if func.attr in _CREATE_METHODS:
                        kind = func.attr
                    elif func.attr in _SPAN_METHODS:
                        kind = "span"
                elif isinstance(func, ast.Name) and \
                        func.id in _CREATE_CTORS:
                    kind = func.id.lower()
                if kind is None:
                    continue
                name = self._literal_name(node.args[0])
                if name is None or not _METRIC_NAME.match(
                        name.replace("*", "x")):
                    continue
                label_keys = self._label_keys(node)
                if label_keys is not None:
                    # labeled instrument: only flattened names exist at
                    # runtime — register the family glob, not the base
                    self._labels_at.setdefault(name, set()).update(
                        label_keys)
                    sites.setdefault(name + ".*", []).append(_Site(
                        rel, node.lineno, "", id(node) in chained_ids,
                        True, kind))
                    continue
                sites.setdefault(name, []).append(_Site(
                    rel, node.lineno, "", id(node) in chained_ids,
                    "*" in name, kind))
        return sites

    @staticmethod
    def _label_keys(node: ast.Call) -> Optional[Set[str]]:
        """Literal label keys of a creation call's ``labels={...}``
        keyword; ``None`` when the call is unlabeled (no kwarg, or a
        literal ``labels=None``)."""
        for kw in node.keywords:
            if kw.arg != "labels":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and v.value is None:
                return None
            keys: Set[str] = set()
            if isinstance(v, ast.Dict):
                keys = {k.value for k in v.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
            return keys  # non-literal dicts: labeled, keys unknown
        return None

    @staticmethod
    def _chained_creations(tree: ast.AST) -> Set[int]:
        """ids of creation Calls that are immediately used —
        ``....counter("x").inc()`` — i.e. created on first use."""
        out: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _USE_METHODS and \
                    isinstance(node.func.value, ast.Call):
                inner = node.func.value
                f = inner.func
                if (isinstance(f, ast.Attribute) and
                        f.attr in _CREATE_METHODS) or \
                        (isinstance(f, ast.Name) and
                         f.id in _CREATE_CTORS):
                    out.add(id(inner))
        return out

    @staticmethod
    def _literal_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.JoinedStr):
            parts = []
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                else:
                    parts.append("*")
            return "".join(parts)
        return None

    def _file_finding(self, rel_display: str, lines: Sequence[str],
                      needle: str, message: str) -> Finding:
        lineno, snippet = 1, ""
        for i, text in enumerate(lines, start=1):
            if needle in text:
                lineno, snippet = i, text.strip()
                break
        return Finding(rule=self.id, path=rel_display, rel=rel_display,
                       line=lineno, col=0, message=message,
                       snippet=snippet)

    # -- checks -------------------------------------------------------------
    @staticmethod
    def _matches_any(pattern: str, sites: Dict[str, List[_Site]]) -> bool:
        tail = pattern.rsplit("/", 1)[-1]  # part-scoped: match the tail
        return any(_pattern_matches_site(tail, name, s[0].is_glob)
                   for name, s in sites.items())

    def _check_baseline(self, root, baseline_path, baseline,
                        baseline_lines, sites, findings) -> None:
        rel = os.path.relpath(baseline_path, root).replace(os.sep, "/")
        for pattern in baseline.get("metrics", {}):
            if self._matches_any(pattern, sites):
                continue
            findings.append(self._file_finding(
                rel, baseline_lines, f'"{pattern}"',
                f"dead threshold: pattern '{pattern}' matches no metric "
                f"creation site anywhere in the repo — it gates nothing "
                f"(renamed metric? remove or re-point it)"))
        for pattern in baseline.get("ignore", []):
            if self._matches_any(pattern, sites):
                continue
            findings.append(self._file_finding(
                rel, baseline_lines, f'"{pattern}"',
                f"dead ignore entry: '{pattern}' matches no metric "
                f"creation site — it hides nothing"))
        for mode, fname in baseline.get("snapshots", {}).items():
            if not os.path.isfile(os.path.join(root, fname)):
                findings.append(self._file_finding(
                    rel, baseline_lines, f'"{fname}"',
                    f"snapshot file '{fname}' (mode '{mode}') does not "
                    f"exist — the drift gate for that bench is vacuous"))

    def _check_alerts(self, root, baseline_path, baseline,
                      baseline_lines, sites, findings) -> None:
        """Alert rules are part of the metric contract (ISSUE 20): a
        rule whose metric (flat or labeled) resolves to no creation site
        can never fire — silently.  Structural problems (unknown keys,
        label keys outside the shared vocabulary) surface through the
        same strict parser the live engine uses, so lint and runtime
        reject identical shapes."""
        doc = baseline.get("alerts")
        if not doc:
            return
        rel = os.path.relpath(baseline_path, root).replace(os.sep, "/")
        try:
            from ..obs.alerts import parse_rules
        except ImportError:
            return
        try:
            rules = parse_rules(doc)
        except ValueError as e:
            findings.append(self._file_finding(
                rel, baseline_lines, '"alerts"',
                f"malformed alert rules: {e}"))
            return
        labels_at = getattr(self, "_labels_at", {})
        for rule in rules:
            flat = rule.flat_metric()
            if not self._matches_any(flat, sites):
                findings.append(self._file_finding(
                    rel, baseline_lines, f'"{rule.name}"',
                    f"dead alert rule '{rule.name}': metric '{flat}' "
                    f"matches no creation site anywhere in the repo — "
                    f"it can never fire (renamed metric? label typo?)"))
                continue
            known = labels_at.get(rule.metric)
            for k in (rule.labels or {}):
                if known and k not in known:
                    findings.append(self._file_finding(
                        rel, baseline_lines, f'"{rule.name}"',
                        f"alert rule '{rule.name}': label key '{k}' is "
                        f"never used at a creation site of "
                        f"'{rule.metric}' (sites label by "
                        f"{sorted(known)}) — likely a typo"))

    def _check_obsview(self, root, sites, findings) -> None:
        path = os.path.join(root, "scripts", "obsview.py")
        if not os.path.isfile(path):
            return
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError):
            return
        lines = source.splitlines()
        seen: Set[str] = set()
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant) and
                    isinstance(node.value, str)):
                continue
            name = node.value
            if name in seen or not _METRIC_NAME.match(name):
                continue
            seen.add(name)
            # prefix reads (startswith/filter keys) match like globs
            matched = any(
                _pattern_matches_site(name, s_name, s[0].is_glob) or
                _pattern_matches_site(name + "*", s_name, s[0].is_glob)
                for s_name, s in sites.items())
            if not matched:
                findings.append(self._file_finding(
                    "scripts/obsview.py", lines, f'"{name}"',
                    f"renderer reads metric '{name}' that no code "
                    f"creates — the panel cell is permanently blank "
                    f"(renamed metric?)"))

    def _check_precreated(self, baseline, sites, findings) -> None:
        """Exactly-gated counters must be pre-created somewhere: if
        EVERY creation site for a gated name is chained
        (create-on-first-use), a run where the path never fires omits
        the metric and the gate silently skips instead of comparing 0.
        Counters with exact literal names only — a templated
        per-instance name (``*.worker3``) cannot be pre-created at init,
        and gauges/histograms are not counter-gated."""
        exact_gates = [
            p.rsplit("/", 1)[-1]
            for p, th in baseline.get("metrics", {}).items()
            if isinstance(th, dict) and
            (th.get("counter_abs") == 0 or th.get("counter_rel") == 0)]
        for name, slist in sorted(sites.items()):
            if "*" in name or not all(
                    s.chained and s.kind == "counter" for s in slist):
                continue
            if not any(fnmatch.fnmatchcase(name, g)
                       for g in exact_gates):
                continue
            s = slist[0]
            findings.append(Finding(
                rule=self.id, path=s.rel, rel=s.rel, line=s.line, col=0,
                message=f"exactly-gated metric '{name}' is only created "
                        f"on first use — pre-create it at init so a run "
                        f"that never fires the path reports 0 instead "
                        f"of omitting the metric (the drift gate skips "
                        f"missing metrics; 0 is present, not missing)",
                snippet=""))


# ---------------------------------------------------------------------------
# handoff-protocol
# ---------------------------------------------------------------------------

class HandoffProtocolRule(ProjectRule):
    id = "handoff-protocol"
    description = ("cross-thread handoff (Thread args / queue.put / "
                   "callback registration) of an object carrying bare "
                   "mutable containers and no lock")

    _PUT_METHODS = {"put", "put_nowait"}

    def check_project(self, graph: ProjectGraph) -> List[Finding]:
        findings: List[Finding] = []
        for fn in graph.functions:
            local_types = graph._local_types(fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                for arg, how in self._handoff_args(node):
                    cls = self._arg_class(graph, fn, arg, local_types)
                    if cls is None:
                        continue
                    if cls.has_any_lock() or not cls.mutable_attrs:
                        continue
                    attrs = ", ".join(sorted(cls.mutable_attrs))
                    findings.append(self.finding(
                        fn.module.ctx, node,
                        f"cross-thread handoff of {cls.name} via {how}: "
                        f"it carries bare mutable state ({attrs}) and "
                        f"owns no lock — add a lock (and guard the "
                        f"mutations) or hand off an immutable snapshot"))
        return findings

    def _handoff_args(self, node: ast.Call):
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if name == "Thread":
            for kw in node.keywords:
                if kw.arg == "args" and isinstance(
                        kw.value, (ast.Tuple, ast.List)):
                    for el in kw.value.elts:
                        yield el, "Thread(args=...)"
        elif isinstance(func, ast.Attribute) and \
                name in self._PUT_METHODS and node.args:
            yield node.args[0], f".{name}()"
        elif ("callback" in name.lower() or "hook" in name.lower()) \
                and node.args:
            for el in node.args:
                yield el, f"{name}(...)"

    @staticmethod
    def _arg_class(graph, fn, arg, local_types):
        from .graph import _dotted
        if isinstance(arg, ast.Name):
            return local_types.get(arg.id)
        if isinstance(arg, ast.Attribute):
            return graph.receiver_class(fn, arg, local_types)
        if isinstance(arg, ast.Call):
            # a fresh `K(...)` handed straight across the boundary
            return graph.resolve_class(fn.module, _dotted(arg.func))
        return None


PROJECT_RULES: Tuple[Rule, ...] = (
    LockOrderCycleRule(),
    MetricContractRule(),
    HandoffProtocolRule(),
)

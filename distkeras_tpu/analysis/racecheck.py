"""Runtime race detector for the PS stack — dklint's dynamic half.

The static ``lock-discipline`` rule reasons lexically; this module checks
the same discipline at runtime on REAL thread interleavings.  ON by
default for the test suite via the autouse pytest fixture in
``tests/conftest.py`` (ISSUE 5 flipped the default after measuring ≈1%
mean overhead on the multiprocess tests); ``DKLINT_RACECHECK=0`` opts
out, with zero overhead when disabled.

Mechanics (a write-focused lockset check, in the Eraser family):

* ``TrackedLock`` wraps a ``threading.Lock`` and records which threads
  currently hold it (re-entrant bookkeeping, so an RLock upgrade keeps
  working).
* ``GuardedDict`` subclasses ``dict``; every mutation checks the guard.
  A mutation WITHOUT the guard held is a violation once the dict has been
  touched by more than one thread — single-threaded setup/teardown stays
  legal (construction and post-join reads have a happens-before edge the
  detector cannot see, so reads are recorded but never flagged).
* ``install()`` monkeypatches ``ParameterServer.__init__`` so every PS
  built afterwards gets a tracked mutex and a guarded
  ``commits_by_worker`` — the shared dict every commit path writes.
  Because shard servers (``ps.shard``, ISSUE 10) ARE ``ParameterServer``
  subclasses, a sharded center gets every shard's mutex and state dicts
  wrapped for free.  ``enabled()`` is the context-manager form tests use.
* **Write-after-publish detection** (ISSUE 10 satellite): the pull cache
  (``ps.state.PullCache``) serves pre-serialized frames whose v2 buffers
  are zero-copy views of the center's arrays — the lock-free
  pull-snapshot contract is that commits REPLACE center arrays, never
  mutate them after they were handed to the cache.  When installed, the
  cache's publish hook fingerprints every published ndarray leaf, and
  each subsequent ``handle_commit`` re-verifies them: a leaf whose bytes
  changed after publish is a recorded violation (a torn frame some
  puller may already be receiving).

Violations land in a process-global list (thread-safe) with the dict
name, key, thread and stack snippet — ``violations()`` / ``reset()``.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import threading
import traceback
from typing import Any, Dict, List, Optional

import numpy as np

ENV_VAR = "DKLINT_RACECHECK"

_VIOLATIONS: List[dict] = []
_VLOCK = threading.Lock()


def violations() -> List[dict]:
    """Snapshot of recorded unguarded-access violations."""
    with _VLOCK:
        return list(_VIOLATIONS)


def reset() -> None:
    with _VLOCK:
        _VIOLATIONS.clear()
        _PUBLISHED.clear()


def _record_violation(name: str, op: str, key: Any) -> None:
    # drop the two racecheck frames; keep the caller's context
    stack = "".join(traceback.format_stack(limit=8)[:-2])
    with _VLOCK:
        _VIOLATIONS.append({
            "dict": name, "op": op, "key": key,
            "thread": threading.current_thread().name,
            "stack": stack,
        })


# ---------------------------------------------------------------------------
# write-after-publish detection (ISSUE 10): the lock-free pull-snapshot
# contract — once a center tree's buffers are handed to the pre-serialized
# pull cache, commits must replace (never mutate) those arrays
# ---------------------------------------------------------------------------

#: id(ps) -> list[(published ndarray, fingerprint, leaf label)] for the
#: LATEST publish per server (older payloads leave the cache when
#: replaced); every touch under _VLOCK.  Strong references are fine: the
#: cache's wire frames keep the arrays alive anyway, and reset() clears.
_PUBLISHED: Dict[int, list] = {}


def _iter_leaves(tree: Any, prefix: str = ""):
    if isinstance(tree, dict):
        for k in sorted(tree, key=str):
            yield from _iter_leaves(tree[k], f"{prefix}{k}/")
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _iter_leaves(v, f"{prefix}{i}/")
    elif isinstance(tree, np.ndarray):
        yield prefix[:-1] if prefix else "", tree


def _fingerprint(arr: np.ndarray) -> bytes:
    return hashlib.blake2b(np.ascontiguousarray(arr).tobytes(),
                           digest_size=8).digest()


def _on_publish(owner: Any, center: Any) -> None:
    """``ps.state`` publish hook: fingerprint every ndarray leaf the
    pull cache just captured for ``owner``'s latest payload."""
    if owner is None or center is None:
        return
    entry = [(arr, _fingerprint(arr), label)
             for label, arr in _iter_leaves(center)]
    with _VLOCK:
        _PUBLISHED[id(owner)] = entry


def _check_published(owner: Any) -> None:
    """Verify the owner's published leaves still hold their published
    bytes; a changed one is a write-after-publish violation (recorded
    once per mutation — the stored fingerprint is refreshed so the same
    corruption is not re-reported every commit)."""
    with _VLOCK:
        entry = _PUBLISHED.get(id(owner))
    if not entry:
        return
    refreshed = []
    for arr, fp, label in entry:
        now = _fingerprint(arr)
        if now != fp:
            _record_violation(f"{type(owner).__name__}.center",
                              "write_after_publish", label)
        refreshed.append((arr, now, label))
    with _VLOCK:
        if _PUBLISHED.get(id(owner)) is entry:
            _PUBLISHED[id(owner)] = refreshed


def enabled_by_env() -> bool:
    """Whether the env asks for racecheck.  ON unless explicitly disabled
    (ISSUE 5 flipped the tier-1 default after measuring ≈1% mean / <7%
    worst-case overhead on the multiprocess tests): ``DKLINT_RACECHECK=0``
    (or ``off``/``false``/``no``/empty) opts out."""
    return os.environ.get(ENV_VAR, "1").lower() not in (
        "", "0", "off", "false", "no")


class TrackedLock:
    """Lock proxy that knows which threads currently hold it."""

    def __init__(self, lock: Optional[threading.Lock] = None):
        self._lock = lock if lock is not None else threading.Lock()
        self._meta = threading.Lock()
        self._holders: Dict[int, int] = {}  # thread id -> depth

    def acquire(self, *args, **kwargs) -> bool:
        got = self._lock.acquire(*args, **kwargs)
        if got:
            tid = threading.get_ident()
            with self._meta:
                self._holders[tid] = self._holders.get(tid, 0) + 1
        return got

    def release(self) -> None:
        tid = threading.get_ident()
        with self._meta:
            depth = self._holders.get(tid, 0)
            if depth <= 1:
                self._holders.pop(tid, None)
            else:
                self._holders[tid] = depth - 1
        self._lock.release()

    def held_by_current_thread(self) -> bool:
        return threading.get_ident() in self._holders

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class GuardedDict(dict):
    """dict that requires ``guard`` to be held for mutations once the
    dict is shared across threads.  Reads record thread participation
    only (post-join single-thread reads are legal and common)."""

    def __init__(self, guard: TrackedLock, name: str, data=()):
        super().__init__(data)
        self._guard = guard
        self._name = name
        self._threads: set = set()
        self._threads.add(threading.get_ident())

    def _touch(self, op: str, key: Any, write: bool) -> None:
        tid = threading.get_ident()
        self._threads.add(tid)  # GIL-atomic set.add
        if write and len(self._threads) > 1 and \
                not self._guard.held_by_current_thread():
            _record_violation(self._name, op, key)

    # -- reads (participation only) ----------------------------------------
    def __getitem__(self, key):
        self._touch("getitem", key, write=False)
        return super().__getitem__(key)

    def get(self, key, default=None):
        self._touch("get", key, write=False)
        return super().get(key, default)

    # -- writes (checked) ---------------------------------------------------
    def __setitem__(self, key, value):
        self._touch("setitem", key, write=True)
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._touch("delitem", key, write=True)
        super().__delitem__(key)

    def pop(self, key, *default):
        self._touch("pop", key, write=True)
        return super().pop(key, *default)

    def popitem(self):
        self._touch("popitem", None, write=True)
        return super().popitem()

    def clear(self):
        self._touch("clear", None, write=True)
        super().clear()

    def update(self, *args, **kwargs):
        self._touch("update", None, write=True)
        super().update(*args, **kwargs)

    def setdefault(self, key, default=None):
        self._touch("setdefault", key, write=True)
        return super().setdefault(key, default)


def wrap_ps(ps) -> None:
    """Instrument one already-built ParameterServer in place: tracked
    mutex + guarded shared dicts (idempotent)."""
    if not isinstance(ps.mutex, TrackedLock):
        ps.mutex = TrackedLock(ps.mutex)
    name = type(ps).__name__
    # every mutex-guarded shared dict, the ISSUE 9 fleet-lifecycle state
    # (generations/tombstones/eviction tallies) included — commit handler
    # threads and the supervisor thread both touch them
    for attr in ("commits_by_worker", "generations", "tombstoned_by_worker",
                 "evictions_by_worker", "respawns_by_worker",
                 "joins_by_worker"):
        cur = getattr(ps, attr, None)
        if cur is not None and not isinstance(cur, GuardedDict):
            setattr(ps, attr, GuardedDict(ps.mutex, f"{name}.{attr}", cur))
    by_worker = getattr(ps, "_h_by_worker", None)
    if by_worker is not None and not isinstance(by_worker, GuardedDict):
        ps._h_by_worker = GuardedDict(ps.mutex, f"{name}._h_by_worker",
                                      by_worker)


def installed() -> bool:
    from ..ps import servers
    return bool(getattr(servers.ParameterServer, "_dklint_racecheck", False))


def install():
    """Monkeypatch every PS ``__init__`` in ``ps.servers`` so each server
    constructed from now on is racechecked.  Patching only the base class
    would wrap BEFORE subclass bodies run (``DynSGDParameterServer``
    creates ``_h_by_worker`` after ``super().__init__``), leaving that
    dict unguarded — so every class in the hierarchy that defines its own
    ``__init__`` is patched and ``wrap_ps`` stays idempotent.  Returns an
    ``uninstall()`` callable."""
    import inspect

    from ..ps import servers

    if installed():
        return lambda: None  # already installed (nested enables)

    targets = [
        cls for _, cls in inspect.getmembers(servers, inspect.isclass)
        if issubclass(cls, servers.ParameterServer) and
        "__init__" in vars(cls)
    ] or [servers.ParameterServer]
    originals = []
    for cls in targets:
        orig_init = cls.__init__

        def patched_init(self, *args, _orig=orig_init, **kwargs):
            _orig(self, *args, **kwargs)
            wrap_ps(self)

        cls.__init__ = patched_init
        originals.append((cls, "__init__", orig_init))
    # methods that REBIND guarded attributes (restore() replaces
    # commits_by_worker with a plain dict) must re-wrap afterwards, or
    # detection silently dies for the rest of the run
    for name in ("restore",):
        orig_m = getattr(servers.ParameterServer, name)

        def rewrapped(self, *args, _orig=orig_m, **kwargs):
            out = _orig(self, *args, **kwargs)
            wrap_ps(self)
            return out

        setattr(servers.ParameterServer, name, rewrapped)
        originals.append((servers.ParameterServer, name, orig_m))
    # write-after-publish (ISSUE 10): observe every pull-cache publish,
    # and re-verify the published leaves after each commit applies — a
    # rule that mutated a published tensor in place (instead of the
    # replace-semantics contract) is caught on its very next commit
    orig_commit = servers.ParameterServer.handle_commit

    def checked_commit(self, *args, _orig=orig_commit, **kwargs):
        out = _orig(self, *args, **kwargs)
        _check_published(self)
        return out

    servers.ParameterServer.handle_commit = checked_commit
    originals.append((servers.ParameterServer, "handle_commit", orig_commit))
    from ..ps import state as ps_state
    prev_hook = ps_state.set_publish_hook(_on_publish)
    servers.ParameterServer._dklint_racecheck = True

    def uninstall():
        for cls, name, orig in originals:
            setattr(cls, name, orig)
        ps_state.set_publish_hook(prev_hook)
        servers.ParameterServer._dklint_racecheck = False

    return uninstall


@contextlib.contextmanager
def enabled():
    """``with racecheck.enabled() as viol:`` — installs the PS proxies,
    yields the live violations list, uninstalls on exit.  The caller
    asserts ``not viol`` (the conftest fixture does exactly this).

    The violation list is scoped to the block: reset on entry AND on
    exit, so a test that deliberately seeds a violation inside a nested
    ``enabled()`` cannot leak it into an outer collector (the autouse
    fixture under ``DKLINT_RACECHECK=1``) and fail teardown spuriously.
    Assert on the yielded list before the block closes."""
    reset()
    uninstall = install()
    try:
        yield _VIOLATIONS
    finally:
        uninstall()
        reset()

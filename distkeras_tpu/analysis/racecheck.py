"""Runtime race detector for the PS stack — dklint's dynamic half.

The static ``lock-discipline`` rule reasons lexically; this module checks
the same discipline at runtime on REAL thread interleavings.  ON by
default for the test suite via the autouse pytest fixture in
``tests/conftest.py`` (ISSUE 5 flipped the default after measuring ≈1%
mean overhead on the multiprocess tests); ``DKLINT_RACECHECK=0`` opts
out, with zero overhead when disabled.

Mechanics (a write-focused lockset check, in the Eraser family):

* ``TrackedLock`` wraps a ``threading.Lock`` and records which threads
  currently hold it (re-entrant bookkeeping, so an RLock upgrade keeps
  working).
* ``GuardedDict`` subclasses ``dict``; every mutation checks the guard.
  A mutation WITHOUT the guard held is a violation once the dict has been
  touched by more than one thread — single-threaded setup/teardown stays
  legal (construction and post-join reads have a happens-before edge the
  detector cannot see, so reads are recorded but never flagged).
* ``install()`` monkeypatches ``__init__`` across the FLEET (ISSUE 18):
  every ``ParameterServer`` subclass plus ``ServeRouter``,
  ``DecodeEngine``, ``KVFabric`` and ``FleetSupervisor`` built afterwards
  get tracked locks and guarded shared containers.  The install registry
  is CLASS-KEYED and idempotent — uninstall restores exactly the
  attributes it patched, per class, so nested enables and partial
  imports can't leak proxies between tests.  ``enabled()`` is the
  context-manager form tests use.
* **Lock-order recording** (ISSUE 18): every named ``TrackedLock`` keeps
  a per-thread held stack; acquiring lock B while holding A records the
  order edge A→B in a process-global graph.  An edge that closes a
  cycle is recorded as a violation THE MOMENT it is observed (two
  threads entering the cycle from different locks can deadlock), and
  ``uninstall`` does a final sweep — the dynamic mirror of the static
  ``lock-order-cycle`` rule.  Re-entry of one lock and edges between
  same-named locks (two shards' mutexes) are not edges.
* **Write-after-publish detection** (ISSUE 10 satellite): the pull cache
  (``ps.state.PullCache``) serves pre-serialized frames whose v2 buffers
  are zero-copy views of the center's arrays — the lock-free
  pull-snapshot contract is that commits REPLACE center arrays, never
  mutate them after they were handed to the cache.  When installed, the
  cache's publish hook fingerprints every published ndarray leaf, and
  each subsequent ``handle_commit`` re-verifies them: a leaf whose bytes
  changed after publish is a recorded violation (a torn frame some
  puller may already be receiving).

Violations land in a process-global list (thread-safe) with the dict
name, key, thread and stack snippet — ``violations()`` / ``reset()``.
"""

from __future__ import annotations

import collections
import contextlib
import hashlib
import os
import threading
import traceback
from typing import Any, Dict, List, Optional

import numpy as np

ENV_VAR = "DKLINT_RACECHECK"

_VIOLATIONS: List[dict] = []
_VLOCK = threading.Lock()


def violations() -> List[dict]:
    """Snapshot of recorded unguarded-access violations."""
    with _VLOCK:
        return list(_VIOLATIONS)


def reset() -> None:
    with _VLOCK:
        _VIOLATIONS.clear()
        _PUBLISHED.clear()
        _LOCK_EDGES.clear()
        _CYCLES_SEEN.clear()


def _record_violation(name: str, op: str, key: Any) -> None:
    # drop the two racecheck frames; keep the caller's context
    stack = "".join(traceback.format_stack(limit=8)[:-2])
    with _VLOCK:
        _VIOLATIONS.append({
            "dict": name, "op": op, "key": key,
            "thread": threading.current_thread().name,
            "stack": stack,
        })


# ---------------------------------------------------------------------------
# write-after-publish detection (ISSUE 10): the lock-free pull-snapshot
# contract — once a center tree's buffers are handed to the pre-serialized
# pull cache, commits must replace (never mutate) those arrays
# ---------------------------------------------------------------------------

#: id(ps) -> list[(published ndarray, fingerprint, leaf label)] for the
#: LATEST publish per server (older payloads leave the cache when
#: replaced); every touch under _VLOCK.  Strong references are fine: the
#: cache's wire frames keep the arrays alive anyway, and reset() clears.
_PUBLISHED: Dict[int, list] = {}


def _iter_leaves(tree: Any, prefix: str = ""):
    if isinstance(tree, dict):
        for k in sorted(tree, key=str):
            yield from _iter_leaves(tree[k], f"{prefix}{k}/")
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _iter_leaves(v, f"{prefix}{i}/")
    elif isinstance(tree, np.ndarray):
        yield prefix[:-1] if prefix else "", tree


def _fingerprint(arr: np.ndarray) -> bytes:
    return hashlib.blake2b(np.ascontiguousarray(arr).tobytes(),
                           digest_size=8).digest()


def _on_publish(owner: Any, center: Any) -> None:
    """``ps.state`` publish hook: fingerprint every ndarray leaf the
    pull cache just captured for ``owner``'s latest payload."""
    if owner is None or center is None:
        return
    entry = [(arr, _fingerprint(arr), label)
             for label, arr in _iter_leaves(center)]
    with _VLOCK:
        _PUBLISHED[id(owner)] = entry


def _check_published(owner: Any) -> None:
    """Verify the owner's published leaves still hold their published
    bytes; a changed one is a write-after-publish violation (recorded
    once per mutation — the stored fingerprint is refreshed so the same
    corruption is not re-reported every commit)."""
    with _VLOCK:
        entry = _PUBLISHED.get(id(owner))
    if not entry:
        return
    refreshed = []
    for arr, fp, label in entry:
        now = _fingerprint(arr)
        if now != fp:
            _record_violation(f"{type(owner).__name__}.center",
                              "write_after_publish", label)
        refreshed.append((arr, now, label))
    with _VLOCK:
        if _PUBLISHED.get(id(owner)) is entry:
            _PUBLISHED[id(owner)] = refreshed


def enabled_by_env() -> bool:
    """Whether the env asks for racecheck.  ON unless explicitly disabled
    (ISSUE 5 flipped the tier-1 default after measuring ≈1% mean / <7%
    worst-case overhead on the multiprocess tests): ``DKLINT_RACECHECK=0``
    (or ``off``/``false``/``no``/empty) opts out."""
    return os.environ.get(ENV_VAR, "1").lower() not in (
        "", "0", "off", "false", "no")


# ---------------------------------------------------------------------------
# lock-order recording (ISSUE 18): the dynamic half of lock-order-cycle
# ---------------------------------------------------------------------------

#: (held lock name, acquired lock name) -> observation count; under _VLOCK
_LOCK_EDGES: Dict[tuple, int] = {}
#: canonical cycle tuples already reported; under _VLOCK
_CYCLES_SEEN: set = set()
_TLS = threading.local()


def _held_stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def lock_order_edges() -> Dict[tuple, int]:
    """Snapshot of the observed acquisition-order graph."""
    with _VLOCK:
        return dict(_LOCK_EDGES)


def _canon_cycle(path: tuple) -> tuple:
    """Rotate a cycle node tuple so the smallest name leads — one
    identity per rotation class."""
    i = path.index(min(path))
    return path[i:] + path[:i]


def _find_cycle(a: str, b: str, edges: Dict[tuple, int]):
    """Path b -> ... -> a in the edge graph, as a cycle tuple starting
    at ``a`` — the cycle the new edge (a, b) would close."""
    adj: Dict[str, list] = {}
    for (u, v) in edges:
        adj.setdefault(u, []).append(v)
    stack = [(b, (a, b))]
    seen = {b}
    while stack:
        node, path = stack.pop()
        for nxt in sorted(adj.get(node, ())):
            if nxt == a:
                return path
            if nxt not in seen and len(path) < 8:
                seen.add(nxt)
                stack.append((nxt, path + (nxt,)))
    return None


def _note_acquired(lock: "TrackedLock") -> None:
    """First (non-reentrant) acquisition by this thread: record order
    edges from every distinctly-named lock the thread already holds,
    flag immediately if one closes a cycle, then push."""
    st = _held_stack()
    cycles = []
    if st:
        held_names = []
        for h in st:
            if h.name != lock.name and h.name not in held_names:
                held_names.append(h.name)
        with _VLOCK:
            for hname in held_names:
                key = (hname, lock.name)
                fresh = key not in _LOCK_EDGES
                _LOCK_EDGES[key] = _LOCK_EDGES.get(key, 0) + 1
                if not fresh:
                    continue
                cyc = _find_cycle(hname, lock.name, _LOCK_EDGES)
                if cyc is not None:
                    canon = _canon_cycle(cyc)
                    if canon not in _CYCLES_SEEN:
                        _CYCLES_SEEN.add(canon)
                        cycles.append(canon)
    for canon in cycles:
        _record_violation("lock-order", "cycle",
                          " -> ".join(canon + (canon[0],)))
    st.append(lock)


def _note_released(lock: "TrackedLock") -> None:
    st = _held_stack()
    for i in range(len(st) - 1, -1, -1):
        if st[i] is lock:
            del st[i]
            break


def _flush_lock_cycles() -> None:
    """Final sweep at uninstall: report any cycle in the observed edge
    graph not already flagged incrementally (belt over suspenders — the
    incremental check fires as edges land)."""
    with _VLOCK:
        edges = dict(_LOCK_EDGES)
    fresh = []
    for (a, b) in sorted(edges):
        cyc = _find_cycle(a, b, edges)
        if cyc is None:
            continue
        canon = _canon_cycle(cyc)
        with _VLOCK:
            if canon in _CYCLES_SEEN:
                continue
            _CYCLES_SEEN.add(canon)
        fresh.append(canon)
    for canon in fresh:
        _record_violation("lock-order", "cycle",
                          " -> ".join(canon + (canon[0],)))


class TrackedLock:
    """Lock proxy that knows which threads currently hold it and feeds
    the global acquisition-order graph (named locks only — an anonymous
    proxy still tracks holders but records no edges)."""

    def __init__(self, lock: Optional[threading.Lock] = None,
                 name: str = ""):
        self._lock = lock if lock is not None else threading.Lock()
        self._meta = threading.Lock()
        self._holders: Dict[int, int] = {}  # thread id -> depth
        self.name = name

    def acquire(self, *args, **kwargs) -> bool:
        got = self._lock.acquire(*args, **kwargs)
        if got:
            tid = threading.get_ident()
            with self._meta:
                depth = self._holders.get(tid, 0)
                self._holders[tid] = depth + 1
            if depth == 0 and self.name:
                _note_acquired(self)
        return got

    def release(self) -> None:
        tid = threading.get_ident()
        with self._meta:
            depth = self._holders.get(tid, 0)
            if depth <= 1:
                self._holders.pop(tid, None)
            else:
                self._holders[tid] = depth - 1
        if depth <= 1 and self.name:
            _note_released(self)
        self._lock.release()

    def held_by_current_thread(self) -> bool:
        return threading.get_ident() in self._holders

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _GuardedMixin:
    """Shared write-lockset bookkeeping for the guarded containers: a
    mutation without the guard held is a violation once the container
    has been touched by more than one thread."""

    def _init_guard(self, guard: TrackedLock, name: str) -> None:
        self._guard = guard
        self._name = name
        self._threads: set = set()
        self._threads.add(threading.get_ident())

    def _touch(self, op: str, key: Any, write: bool) -> None:
        tid = threading.get_ident()
        self._threads.add(tid)  # GIL-atomic set.add
        if write and len(self._threads) > 1 and \
                not self._guard.held_by_current_thread():
            _record_violation(self._name, op, key)


class GuardedDict(_GuardedMixin, dict):
    """dict that requires ``guard`` to be held for mutations once the
    dict is shared across threads.  Reads record thread participation
    only (post-join single-thread reads are legal and common)."""

    def __init__(self, guard: TrackedLock, name: str, data=()):
        super().__init__(data)
        self._init_guard(guard, name)

    # -- reads (participation only) ----------------------------------------
    def __getitem__(self, key):
        self._touch("getitem", key, write=False)
        return super().__getitem__(key)

    def get(self, key, default=None):
        self._touch("get", key, write=False)
        return super().get(key, default)

    # -- writes (checked) ---------------------------------------------------
    def __setitem__(self, key, value):
        self._touch("setitem", key, write=True)
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._touch("delitem", key, write=True)
        super().__delitem__(key)

    def pop(self, key, *default):
        self._touch("pop", key, write=True)
        return super().pop(key, *default)

    def popitem(self):
        self._touch("popitem", None, write=True)
        return super().popitem()

    def clear(self):
        self._touch("clear", None, write=True)
        super().clear()

    def update(self, *args, **kwargs):
        self._touch("update", None, write=True)
        super().update(*args, **kwargs)

    def setdefault(self, key, default=None):
        self._touch("setdefault", key, write=True)
        return super().setdefault(key, default)


class GuardedOrderedDict(_GuardedMixin, collections.OrderedDict):
    """OrderedDict under the same write-lockset check — covers the
    router's LRU affinity table (``move_to_end`` / LRU ``popitem`` are
    writes too: they mutate the order the eviction scan relies on)."""

    def __init__(self, guard: TrackedLock, name: str, data=()):
        super().__init__(data)
        self._init_guard(guard, name)

    def __getitem__(self, key):
        self._touch("getitem", key, write=False)
        return super().__getitem__(key)

    def get(self, key, default=None):
        self._touch("get", key, write=False)
        return super().get(key, default)

    def __setitem__(self, key, value):
        # OrderedDict.__init__/__reduce__ call __setitem__ before our
        # guard exists — pass construction-time writes through
        if hasattr(self, "_guard"):
            self._touch("setitem", key, write=True)
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._touch("delitem", key, write=True)
        super().__delitem__(key)

    def pop(self, key, *default):
        self._touch("pop", key, write=True)
        return super().pop(key, *default)

    def popitem(self, last=True):
        self._touch("popitem", None, write=True)
        return super().popitem(last=last)

    def move_to_end(self, key, last=True):
        self._touch("move_to_end", key, write=True)
        return super().move_to_end(key, last=last)

    def clear(self):
        self._touch("clear", None, write=True)
        super().clear()

    def update(self, *args, **kwargs):
        self._touch("update", None, write=True)
        super().update(*args, **kwargs)

    def setdefault(self, key, default=None):
        self._touch("setdefault", key, write=True)
        return super().setdefault(key, default)


class GuardedSet(_GuardedMixin, set):
    """set under the same write-lockset check — the fabric's
    single-flight key set."""

    def __init__(self, guard: TrackedLock, name: str, data=()):
        super().__init__(data)
        self._init_guard(guard, name)

    def add(self, item):
        self._touch("add", item, write=True)
        super().add(item)

    def discard(self, item):
        self._touch("discard", item, write=True)
        super().discard(item)

    def remove(self, item):
        self._touch("remove", item, write=True)
        super().remove(item)

    def pop(self):
        self._touch("pop", None, write=True)
        return super().pop()

    def clear(self):
        self._touch("clear", None, write=True)
        super().clear()

    def update(self, *args):
        self._touch("update", None, write=True)
        super().update(*args)


def wrap_ps(ps) -> None:
    """Instrument one already-built ParameterServer in place: tracked
    mutex + guarded shared dicts (idempotent)."""
    if not isinstance(ps.mutex, TrackedLock):
        ps.mutex = TrackedLock(ps.mutex, name="ParameterServer.mutex")
    name = type(ps).__name__
    # every mutex-guarded shared dict, the ISSUE 9 fleet-lifecycle state
    # (generations/tombstones/eviction tallies) included — commit handler
    # threads and the supervisor thread both touch them
    for attr in ("commits_by_worker", "generations", "tombstoned_by_worker",
                 "evictions_by_worker", "respawns_by_worker",
                 "joins_by_worker"):
        cur = getattr(ps, attr, None)
        if cur is not None and not isinstance(cur, GuardedDict):
            setattr(ps, attr, GuardedDict(ps.mutex, f"{name}.{attr}", cur))
    by_worker = getattr(ps, "_h_by_worker", None)
    if by_worker is not None and not isinstance(by_worker, GuardedDict):
        ps._h_by_worker = GuardedDict(ps.mutex, f"{name}._h_by_worker",
                                      by_worker)


# ---------------------------------------------------------------------------
# fleet wrap functions (ISSUE 18): one per instrumented class, each
# idempotent — install patches the class __init__ to call these
# ---------------------------------------------------------------------------

def wrap_router(r) -> None:
    """ServeRouter: routing lock + promote lock tracked, the LRU
    affinity table guarded (owner lists, ``move_to_end`` ordering and
    LRU eviction are all ``_lock``-protected state)."""
    if not isinstance(r._lock, TrackedLock):
        r._lock = TrackedLock(r._lock, name="ServeRouter._lock")
    if not isinstance(r._promote_lock, TrackedLock):
        r._promote_lock = TrackedLock(r._promote_lock,
                                      name="ServeRouter._promote_lock")
    if not isinstance(r._affinity, GuardedOrderedDict):
        r._affinity = GuardedOrderedDict(r._lock, "ServeRouter._affinity",
                                         r._affinity)


def wrap_engine(e) -> None:
    """DecodeEngine: tracked queue lock.  The engine's ``_work``
    condition wraps ``_lock`` — it must be REBUILT over the proxy, or
    ``wait()`` would release the raw lock while the proxy still thinks
    it is held and every subsequent lockset check lies."""
    if not isinstance(e._lock, TrackedLock):
        e._lock = TrackedLock(e._lock, name="DecodeEngine._lock")
        e._work = threading.Condition(e._lock)


def wrap_fabric(f) -> None:
    """KVFabric: tracked job lock (condition rebuilt, see wrap_engine),
    guarded single-flight set and per-link job counts."""
    if not isinstance(f._lock, TrackedLock):
        f._lock = TrackedLock(f._lock, name="KVFabric._lock")
        f._work = threading.Condition(f._lock)
    if not isinstance(f._inflight, GuardedSet):
        f._inflight = GuardedSet(f._lock, "KVFabric._inflight",
                                 f._inflight)
    if not isinstance(f._link_jobs, GuardedDict):
        f._link_jobs = GuardedDict(f._lock, "KVFabric._link_jobs",
                                   f._link_jobs)


def wrap_supervisor(s) -> None:
    """FleetSupervisor: tracked fleet lock + guarded incarnation maps
    (the supervisor poll loop and concurrent ``add_worker`` callers both
    write them)."""
    if not isinstance(s._lock, TrackedLock):
        s._lock = TrackedLock(s._lock, name="FleetSupervisor._lock")
    for attr in ("live", "attempts", "finished"):
        cur = getattr(s, attr, None)
        if cur is not None and not isinstance(cur, GuardedDict):
            setattr(s, attr, GuardedDict(s._lock,
                                         f"FleetSupervisor.{attr}", cur))


#: class -> [(attr name, original value)] for everything install patched;
#: the CLASS-KEYED registry that makes uninstall exact and idempotent
_INSTALLED: Dict[type, list] = {}


def installed() -> bool:
    return bool(_INSTALLED)


def _patch_init(cls, wrap, originals: list) -> None:
    orig_init = cls.__init__

    def patched_init(self, *args, _orig=orig_init, _wrap=wrap, **kwargs):
        _orig(self, *args, **kwargs)
        _wrap(self)

    cls.__init__ = patched_init
    originals.append((cls, "__init__", orig_init))


def install():
    """Monkeypatch ``__init__`` across the instrumented fleet so every
    object constructed from now on is racechecked.

    PS servers: patching only the base class would wrap BEFORE subclass
    bodies run (``DynSGDParameterServer`` creates ``_h_by_worker`` after
    ``super().__init__``), leaving that dict unguarded — so every class
    in the hierarchy that defines its own ``__init__`` is patched and
    ``wrap_ps`` stays idempotent.  Serving fleet (ISSUE 18):
    ``ServeRouter``, ``DecodeEngine``, ``KVFabric``,
    ``FleetSupervisor`` get the same treatment (the router's fabric is
    built inside ``ServeRouter.__init__`` — the fabric's own patched
    ``__init__`` wraps it first, and its dynamic reads of
    ``router._lock`` see the proxy installed a moment later, before any
    fabric thread starts).

    Everything patched is recorded CLASS-KEYED in ``_INSTALLED``;
    ``uninstall()`` restores exactly those attributes and nothing else.
    Returns the ``uninstall()`` callable (a no-op when already
    installed — nested enables uninstall once, at the outermost exit)."""
    import inspect

    from ..ps import servers

    if installed():
        return lambda: None  # already installed (nested enables)

    originals: list = []
    targets = [
        cls for _, cls in inspect.getmembers(servers, inspect.isclass)
        if issubclass(cls, servers.ParameterServer) and
        "__init__" in vars(cls)
    ] or [servers.ParameterServer]
    for cls in targets:
        _patch_init(cls, wrap_ps, originals)
    # methods that REBIND guarded attributes (restore() replaces
    # commits_by_worker with a plain dict) must re-wrap afterwards, or
    # detection silently dies for the rest of the run
    for name in ("restore",):
        orig_m = getattr(servers.ParameterServer, name)

        def rewrapped(self, *args, _orig=orig_m, **kwargs):
            out = _orig(self, *args, **kwargs)
            wrap_ps(self)
            return out

        setattr(servers.ParameterServer, name, rewrapped)
        originals.append((servers.ParameterServer, name, orig_m))
    # write-after-publish (ISSUE 10): observe every pull-cache publish,
    # and re-verify the published leaves after each commit applies — a
    # rule that mutated a published tensor in place (instead of the
    # replace-semantics contract) is caught on its very next commit
    orig_commit = servers.ParameterServer.handle_commit

    def checked_commit(self, *args, _orig=orig_commit, **kwargs):
        out = _orig(self, *args, **kwargs)
        _check_published(self)
        return out

    servers.ParameterServer.handle_commit = checked_commit
    originals.append((servers.ParameterServer, "handle_commit", orig_commit))

    # the serving/fleet classes (ISSUE 18) — imported lazily; a partial
    # environment (e.g. serve deps absent) degrades to the PS-only set
    fleet_specs = [
        ("..serve.router", "ServeRouter", wrap_router),
        ("..serve.engine", "DecodeEngine", wrap_engine),
        ("..serve.kvfabric", "KVFabric", wrap_fabric),
        ("..ps.runner", "FleetSupervisor", wrap_supervisor),
    ]
    import importlib
    for modname, clsname, wrap in fleet_specs:
        try:
            mod = importlib.import_module(modname, package=__package__)
            cls = getattr(mod, clsname)
        except (ImportError, AttributeError):
            continue
        _patch_init(cls, wrap, originals)

    from ..ps import state as ps_state
    prev_hook = ps_state.set_publish_hook(_on_publish)
    servers.ParameterServer._dklint_racecheck = True
    for cls, attr, orig in originals:
        _INSTALLED.setdefault(cls, []).append((attr, orig))

    def uninstall():
        _flush_lock_cycles()  # report observed lock-order cycles
        for cls, patched in list(_INSTALLED.items()):
            for attr, orig in reversed(patched):
                setattr(cls, attr, orig)
            del _INSTALLED[cls]
        ps_state.set_publish_hook(prev_hook)
        servers.ParameterServer._dklint_racecheck = False

    return uninstall


@contextlib.contextmanager
def enabled():
    """``with racecheck.enabled() as viol:`` — installs the PS proxies,
    yields the live violations list, uninstalls on exit.  The caller
    asserts ``not viol`` (the conftest fixture does exactly this).

    The violation list is scoped to the block: reset on entry AND on
    exit, so a test that deliberately seeds a violation inside a nested
    ``enabled()`` cannot leak it into an outer collector (the autouse
    fixture under ``DKLINT_RACECHECK=1``) and fail teardown spuriously.
    Assert on the yielded list before the block closes."""
    reset()
    uninstall = install()
    try:
        yield _VIOLATIONS
    finally:
        uninstall()
        reset()

"""dklint command line — ``dklint [paths...]`` (console entry point) or
``python scripts/dklint.py [paths...]``.

Exit codes: 0 = clean (no unsuppressed findings), 1 = findings,
2 = usage/IO error.  ``--format json`` emits a machine-readable report;
``--write-baseline`` accepts the current findings as debt (see
``core.write_baseline``).  With no ``--baseline`` flag, the nearest
``dklint_baseline.json`` above the scanned paths (or cwd) is picked up
automatically, so the committed baseline is honored no matter which
directory ``dklint`` runs from.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from ..obs.logging import emit
from . import core
from .rules import ALL_RULES, RULES_BY_ID

_DEFAULT_BASELINE = "dklint_baseline.json"


def _discover_baseline(paths: List[str]) -> Optional[str]:
    """Nearest ``dklint_baseline.json`` above the scanned paths (falling
    back to cwd): running the installed ``dklint`` from any directory
    still honors the scanned repo's committed baseline.  Paths come
    first — the caller's cwd may sit in a DIFFERENT repo whose baseline
    must not shadow the target's."""
    for start in [p for p in paths if os.path.exists(p)] + [os.getcwd()]:
        anchor = core.find_anchor(start)
        while anchor is not None:
            cand = os.path.join(anchor, _DEFAULT_BASELINE)
            if os.path.exists(cand):
                return cand
            parent = os.path.dirname(anchor)
            anchor = core.find_anchor(parent) if parent != anchor else None
    return None


def _changed_files(base: str, paths: List[str]) -> Optional[List[str]]:
    """``git diff --name-only <base>`` filtered to Python files that
    still exist AND fall under one of the requested ``paths`` — the
    fast pre-commit loop (``--changed``) shares every other flag with
    the repo-wide gate.  None on git failure (caller reports usage
    error)."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "-z", base, "--"],
            capture_output=True, check=True)
    except (OSError, subprocess.CalledProcessError):
        return None
    roots = [os.path.abspath(p) for p in paths]
    picked = []
    for name in out.stdout.decode("utf-8", "replace").split("\0"):
        if not name.endswith(".py") or not os.path.exists(name):
            continue
        full = os.path.abspath(name)
        if any(full == r or full.startswith(r + os.sep) for r in roots):
            picked.append(name)
    return picked


def _select_rules(spec: Optional[str]) -> List[core.Rule]:
    if not spec:
        return list(ALL_RULES)
    rules = []
    for rid in (s.strip() for s in spec.split(",") if s.strip()):
        if rid not in RULES_BY_ID:
            raise KeyError(rid)
        rules.append(RULES_BY_ID[rid])
    return rules


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dklint",
        description="static analysis for the distkeras_tpu stack "
                    "(jit-purity, lock-discipline, swallow-guard, "
                    "thread-shutdown, bare-print)")
    ap.add_argument("paths", nargs="*", default=["distkeras_tpu"],
                    help="files/directories to analyze "
                         "(default: distkeras_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--rules", metavar="ID[,ID...]",
                    help="run only these rules (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--baseline", metavar="FILE",
                    help=f"suppression baseline (default: "
                         f"./{_DEFAULT_BASELINE} when present)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings as the baseline "
                         "and write them to the baseline file")
    ap.add_argument("--changed", nargs="?", const="HEAD", metavar="REF",
                    help="lint only files changed vs REF (git diff "
                         "--name-only; default HEAD), intersected with "
                         "the given paths")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parse and per-file-check N files in parallel "
                         "(interprocedural rules still run once, over "
                         "the whole set)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            emit(f"{r.id:16s} {r.description}")
        return 0

    try:
        rules = _select_rules(args.rules)
    except KeyError as e:
        emit(f"dklint: unknown rule {e.args[0]!r} "
             f"(known: {', '.join(sorted(RULES_BY_ID))})", err=True)
        return 2

    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = _discover_baseline(args.paths)

    scan_paths = list(args.paths)
    if args.changed is not None:
        changed = _changed_files(args.changed, scan_paths)
        if changed is None:
            emit(f"dklint: git diff against {args.changed!r} failed "
                 f"(not a git checkout, or unknown ref)", err=True)
            return 2
        if not changed:
            emit("dklint: no changed Python files under the given paths")
            return 0
        scan_paths = changed

    write_target = None
    bootstrap = None
    if args.write_baseline and args.rules:
        # a subset run would overwrite the baseline with only ITS
        # findings, silently dropping every other rule's accepted debt
        emit("dklint: --write-baseline requires the full rule set "
             "(drop --rules)", err=True)
        return 2
    if args.write_baseline and args.changed is not None:
        # same trap, file axis: a changed-only scan would overwrite the
        # baseline with only the changed files' findings
        emit("dklint: --write-baseline requires a full scan "
             "(drop --changed)", err=True)
        return 2
    if args.write_baseline:
        write_target = args.baseline or baseline_path or _DEFAULT_BASELINE
        if not os.path.exists(write_target):
            # create it BEFORE scanning: the baseline file is itself an
            # anchor marker, so the fingerprints it stores must be
            # computed with it in place (first-write bootstrap)
            core.write_baseline(write_target, [])
            bootstrap = write_target

    report = core.run_paths(scan_paths, rules=rules,
                            jobs=max(1, args.jobs))
    if report.errors:
        if bootstrap is not None:
            # don't leave a stray empty baseline behind on a failed run —
            # as an anchor marker it would re-root future fingerprints
            try:
                os.unlink(bootstrap)
            except OSError:
                pass
        for path, msg in report.errors:
            emit(f"dklint: {path}: {msg}", err=True)
        return 2

    if write_target is not None:
        core.write_baseline(write_target, report.findings)
        emit(f"dklint: wrote {len(report.findings)} finding(s) to "
             f"{write_target}")
        return 0

    if baseline_path is not None:
        try:
            core.apply_baseline(report, core.load_baseline(baseline_path))
        except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
            emit(f"dklint: bad baseline {baseline_path}: {e}", err=True)
            return 2

    if args.format == "json":
        emit(json.dumps({
            "findings": [f.as_dict() for f in report.findings],
            "suppressed": {
                "inline": len(report.inline_suppressed),
                "baseline": len(report.baseline_suppressed),
            },
        }, indent=2))
    else:
        for f in report.findings:
            emit(f"{f.location()}: [{f.rule}] {f.message}")
            if f.snippet:
                emit(f"    {f.snippet}")
        n = len(report.findings)
        supp = len(report.inline_suppressed) + len(report.baseline_suppressed)
        tail = f" ({supp} suppressed)" if supp else ""
        emit(f"dklint: {n} finding(s){tail}")
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())

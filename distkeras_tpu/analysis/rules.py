"""dklint rules — repo-specific static checks for a distributed-JAX stack.

Nine rules, each targeting a hazard class this codebase actually has
(ISSUE 3; the PS stack is exactly the shape of code where these corrupt
training without failing a test):

* ``jit-purity``      — Python side effects / host syncs inside functions
  that are jit-traced (``time.time()``, global-state ``np.random.*``,
  ``.item()``, ``float()``, ``np.asarray``, ``block_until_ready``, ...).
  Traced code runs ONCE at trace time; a side effect there silently bakes
  one stale value into the compiled program.
* ``lock-discipline`` — for classes owning a ``threading.Lock``, instance
  attributes written both under ``with <lock>`` and bare.  A method whose
  contract is "called with the lock held" declares it with a
  ``# dklint: holds=<lock>`` pragma on its ``def`` line.
* ``swallow-guard``   — catch-all handlers (``except:`` /
  ``except Exception:``) that neither re-raise, nor use the bound
  exception, nor log: the silent-corruption classic.
* ``thread-shutdown`` — daemon threads spawned in a scope with no stop
  event and no ``join()``: work that dies mid-write at interpreter exit.
* ``bare-print``      — ``print(`` in library code (output goes through
  ``obs.logging``'s ``emit``/``get_logger`` seam); migrated here from
  the one-off AST gate PR 2 shipped in ``tests/test_obs.py``.
* ``staleness-protocol`` — commits built from a center pulled BEFORE the
  previous commit's reply (ISSUE 6, carried from ROADMAP): a ``commit``
  repeated — in a loop, or back-to-back — without a fresh ``pull`` on
  the same receiver trains every window after the first against a stale
  center.  The async algorithms' contract is pull-per-window; this is
  the lexical check for the one protocol slip a test's loss curve
  rarely catches.
* ``shm-lifecycle`` — ``multiprocessing.shared_memory`` segments created
  (``SharedMemory(create=True)`` / ``ShmRing.create``) in a scope with
  no ``unlink`` on any shutdown path (ISSUE 12): a POSIX shm segment
  outlives the process — close() releases the mapping but only the
  creator's unlink() releases the /dev/shm backing, so a leak persists
  until reboot.  Attach-only scopes (which must NOT unlink — the
  creator owns that) are not flagged.
* ``wire-seam`` — raw ``.recv(`` / ``.recv_into(`` / ``.sendall(`` /
  ``.sendmsg(`` calls outside ``ps/networking.py`` (ISSUE 15): every
  wire byte must travel the one networking seam — it carries the
  v1/v2/shm/stream framing, the chaos fault-injection hook, and the
  ``net.*`` byte counters.  A raw socket call elsewhere ships bytes the
  fault harness cannot reset, the byte ledgers never see, and the frame
  auto-detection cannot parse.
* ``kv-version-guard`` — ``insert_remote(`` calls outside
  ``serve/kvfabric.py`` (ISSUE 16): a remote KV pytree may only enter a
  ``PrefixCache`` through the fabric's version-guarded seam
  (``admit_remote_entry`` — checkpoint stamp checked before the insert
  and re-checked after).  An insert elsewhere can land KV computed
  under different weights, which then serves WRONG tokens — the one
  fleet-cache bug no output test reliably catches, because the stale
  entry only fires when its exact prefix recurs after a promote.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import FileContext, Finding, Rule

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> Optional[str]:
    """Name/Attribute chain -> dotted string (``jax.jit``), else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> Optional[str]:
    """Last component of a Name/Attribute chain (``jax.jit`` -> ``jit``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------

#: transforms whose function argument gets traced (first positional arg or
#: decorator target); ``scan`` covers ``lax.scan(body, ...)`` bodies
_TRACE_NAMES = {"jit", "pjit", "pmap", "vmap", "grad", "value_and_grad",
                "shard_map", "checkpoint", "remat", "scan"}

#: ``time.X()`` calls that read host clocks / sleep
_TIME_FNS = {"time", "time_ns", "perf_counter", "perf_counter_ns",
             "monotonic", "monotonic_ns", "process_time", "sleep"}

#: ``np.X()`` host materialization / IO
_NP_HOST = {"asarray", "array", "save", "savez", "savez_compressed", "load"}

#: method calls that force a device->host sync
_SYNC_METHODS = {"item", "tolist", "block_until_ready", "numpy"}

#: builtins that concretize a traced value on the host
_CAST_BUILTINS = {"float", "int", "bool"}


def _is_trace_transform(node: ast.AST) -> bool:
    """True for ``jax.jit`` / ``jit`` / ``functools.partial(jax.jit, ...)``
    / ``jax.jit(static_argnums=...)`` decorator expressions."""
    if _terminal(node) in _TRACE_NAMES:
        return True
    if isinstance(node, ast.Call):
        if _terminal(node.func) == "partial" and node.args and \
                _terminal(node.args[0]) in _TRACE_NAMES:
            return True
        if _terminal(node.func) in _TRACE_NAMES:
            return True
    return False


class JitPurityRule(Rule):
    id = "jit-purity"
    description = ("side effects / host syncs inside jit-traced functions "
                   "(run once at trace time, then baked into the program; "
                   "functions CALLED from a traced body are traced too — "
                   "followed one call-edge level)")

    def check(self, ctx: FileContext) -> List[Finding]:
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)

        traced: List[ast.AST] = []
        seen_ids: Set[int] = set()

        def mark(fn: ast.AST) -> None:
            if id(fn) not in seen_ids:
                seen_ids.add(id(fn))
                traced.append(fn)

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_trace_transform(d) for d in node.decorator_list):
                    mark(node)
            elif isinstance(node, ast.Call) and \
                    _terminal(node.func) in _TRACE_NAMES and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name):
                    for fn in defs.get(arg.id, []):
                        mark(fn)

        # one-level call-edge following (ISSUE 7, carried ROADMAP item):
        # a function invoked BY NAME from a traced body runs at trace time
        # too — its clock/RNG/sync violations bake into the program just
        # the same.  One level only: deeper chains trade signal for noise
        # (and same-name resolution is already heuristic); attribute
        # calls (self.f(), module.f()) stay unresolved by design.
        for fn in list(traced):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name):
                    for callee in defs.get(node.func.id, []):
                        mark(callee)

        findings: List[Finding] = []
        flagged: Set[Tuple[int, int]] = set()

        def flag(node: ast.AST, what: str) -> None:
            key = (node.lineno, node.col_offset)
            if key in flagged:
                return
            flagged.add(key)
            findings.append(self.finding(
                ctx, node, f"{what} inside a jit-traced function (runs "
                           f"once at trace time, not per step)"))

        for fn in traced:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted(node.func) or ""
                parts = dotted.split(".")
                term = parts[-1] if parts else ""
                # time.time() and friends
                if len(parts) == 2 and parts[0] == "time" and \
                        term in _TIME_FNS:
                    flag(node, f"host clock call `{dotted}()`")
                # np.random.* global-state RNG (default_rng is the seeded,
                # object-based API — still host-side, but flagged as a
                # host materialization only when its output is consumed)
                elif len(parts) == 3 and parts[0] in ("np", "numpy") and \
                        parts[1] == "random" and term != "default_rng":
                    flag(node, f"global-state RNG `{dotted}()` (use "
                               f"jax.random with an explicit key)")
                # np.asarray / np.array / np IO — host materialization
                elif len(parts) == 2 and parts[0] in ("np", "numpy") and \
                        term in _NP_HOST:
                    flag(node, f"host materialization `{dotted}()` (use "
                               f"jnp inside traced code)")
                # .item() / .block_until_ready() / .tolist() / .numpy() —
                # checked on node.func.attr, not the dotted chain: the
                # common shapes (`loss.mean().item()`,
                # `state['loss'].item()`) have Call/Subscript receivers
                # that don't form a Name chain
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _SYNC_METHODS and not node.args:
                    flag(node, f"device->host sync `.{node.func.attr}()`")
                # float(x) / int(x) / bool(x) on non-literals
                elif isinstance(node.func, ast.Name) and \
                        term in _CAST_BUILTINS and node.args and \
                        not isinstance(node.args[0], ast.Constant):
                    flag(node, f"host concretization `{term}(...)`")
                elif isinstance(node.func, ast.Name) and term == "print":
                    flag(node, "print() side effect")
        return findings


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

#: container methods that mutate their receiver
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
             "popleft", "appendleft", "clear", "update", "setdefault",
             "add", "discard", "sort", "reverse"}

_LOCK_CTORS = {"Lock", "RLock"}


class _ClassRecord:
    """Per-class write ledger: attr -> write sites split by lock state."""

    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.bases = [_terminal(b) for b in node.bases]
        self.locks: Set[str] = set()
        #: attr -> lock names it was written under
        self.inside: Dict[str, Set[str]] = {}
        #: attr -> [(write node, method name)] for unguarded writes
        self.outside: Dict[str, List[Tuple[ast.AST, str]]] = {}


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    description = ("instance attributes written both under `with <lock>` "
                   "and bare, in classes that own a threading.Lock")

    def check(self, ctx: FileContext) -> List[Finding]:
        classes: Dict[str, _ClassRecord] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                classes[node.name] = _ClassRecord(node)

        for rec in classes.values():
            for node in ast.walk(rec.node):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr and isinstance(getattr(node, "value", None),
                                           ast.Call) and \
                            _terminal(node.value.func) in _LOCK_CTORS:
                        rec.locks.add(attr)

        def chain_locks(rec: _ClassRecord, depth: int = 0) -> Set[str]:
            locks = set(rec.locks)
            if depth < 8:  # defensive bound on malformed hierarchies
                for b in rec.bases:
                    if b in classes:
                        locks |= chain_locks(classes[b], depth + 1)
            return locks

        for rec in classes.values():
            locks = chain_locks(rec)
            if not locks:
                continue
            for item in rec.node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if item.name == "__init__":
                    continue  # construction happens-before every thread
                self._scan(rec, item, locks,
                           held=set(ctx.holds(item.lineno)))

        findings: List[Finding] = []

        def chain_inside(rec: _ClassRecord,
                         depth: int = 0) -> Dict[str, Set[str]]:
            """attr -> lock names it is written under, across the local
            class hierarchy (a subclass writing bare to an attribute the
            base guards is exactly the bug this rule exists for)."""
            out: Dict[str, Set[str]] = {}
            if depth < 8:
                for b in rec.bases:
                    if b in classes:
                        for a, ls in chain_inside(classes[b],
                                                  depth + 1).items():
                            out.setdefault(a, set()).update(ls)
            for a, ls in rec.inside.items():
                out.setdefault(a, set()).update(ls)
            return out

        for rec in classes.values():
            if not chain_locks(rec):
                continue
            guarded = chain_inside(rec)
            for attr, sites in sorted(rec.outside.items()):
                if attr not in guarded:
                    continue
                locks_txt = ",".join(sorted(guarded[attr])) or \
                    ",".join(sorted(chain_locks(rec)))
                for node, method in sites:
                    findings.append(self.finding(
                        ctx, node,
                        f"`self.{attr}` written in `{rec.node.name}."
                        f"{method}` without holding `self.{locks_txt}` "
                        f"(written under the lock elsewhere); guard it, "
                        f"or declare the contract with "
                        f"`# dklint: holds={locks_txt}`"))
        return findings

    def _scan(self, rec: _ClassRecord, method: ast.AST, locks: Set[str],
              held: Set[str]) -> None:
        """Walk one method body tracking which owned locks are lexically
        held; record every self-attribute write on the proper side."""

        def record(node: ast.AST, attr: str, held_now: Set[str]) -> None:
            if attr in locks:
                return  # rebinding the lock itself is not data
            if held_now:
                rec.inside.setdefault(attr, set()).update(held_now)
            else:
                rec.outside.setdefault(attr, []).append((node, method.name))

        def write_targets(node: ast.AST) -> List[str]:
            attrs = []
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for e in elts:
                    a = _self_attr(e)
                    if a:
                        attrs.append(a)
                    elif isinstance(e, ast.Subscript):
                        a = _self_attr(e.value)
                        if a:
                            attrs.append(a)
            return attrs

        def visit(node: ast.AST, held_now: Set[str]) -> None:
            if isinstance(node, ast.With):
                acquired = set()
                for item in node.items:
                    a = _self_attr(item.context_expr)
                    if a in locks:
                        acquired.add(a)
                for child in node.body:
                    visit(child, held_now | acquired)
                return
            for attr in write_targets(node):
                record(node, attr, held_now)
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value)
                if attr:
                    record(node, attr, held_now)
            for child in ast.iter_child_nodes(node):
                visit(child, held_now)

        for child in method.body:
            visit(child, set(held))


# ---------------------------------------------------------------------------
# swallow-guard
# ---------------------------------------------------------------------------

#: calls that count as "the handler tells someone": logging, tracebacks,
#: the library's console seam
_DIAGNOSTIC_CALLS = {"print_exc", "print_exception", "format_exc", "emit",
                     "warning", "warn", "error", "exception", "log",
                     "debug", "info", "critical", "fail"}


class SwallowGuardRule(Rule):
    id = "swallow-guard"
    description = ("catch-all except handlers that neither re-raise, use "
                   "the exception, nor log it")

    def check(self, ctx: FileContext) -> List[Finding]:
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._catches_all(node.type):
                continue
            if self._handled(node):
                continue
            what = "bare `except:`" if node.type is None else \
                f"`except {_dotted(node.type) or 'Exception'}:`"
            findings.append(self.finding(
                ctx, node,
                f"{what} swallows every error silently; catch specific "
                f"exception types, or log/re-raise what you catch"))
        return findings

    @staticmethod
    def _catches_all(type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return True
        elts = type_node.elts if isinstance(type_node, ast.Tuple) \
            else [type_node]
        return any(_terminal(e) in ("Exception", "BaseException")
                   for e in elts)

    @staticmethod
    def _handled(handler: ast.ExceptHandler) -> bool:
        for node in handler.body:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Raise):
                    return True
                if handler.name and isinstance(sub, ast.Name) and \
                        sub.id == handler.name:
                    return True  # bound exception is used (stored/wrapped)
                if isinstance(sub, ast.Call) and \
                        _terminal(sub.func) in _DIAGNOSTIC_CALLS:
                    return True
        return False


# ---------------------------------------------------------------------------
# thread-shutdown
# ---------------------------------------------------------------------------


class ThreadShutdownRule(Rule):
    id = "thread-shutdown"
    description = ("daemon threads spawned in a scope with no stop event "
                   "and no join(): dies mid-write at interpreter exit")

    def check(self, ctx: FileContext) -> List[Finding]:
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node

        def scope_of(node: ast.AST) -> ast.AST:
            """Nearest enclosing ClassDef, else the outermost FunctionDef,
            else the module — the region where a stop/join path for this
            thread would plausibly live."""
            cur, outer_fn = node, None
            while id(cur) in parents:
                cur = parents[id(cur)]
                if isinstance(cur, ast.ClassDef):
                    return cur
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    outer_fn = cur
            return outer_fn if outer_fn is not None else ctx.tree

        findings = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    _terminal(node.func) == "Thread"):
                continue
            daemon = any(kw.arg == "daemon" and
                         isinstance(kw.value, ast.Constant) and
                         kw.value.value is True for kw in node.keywords)
            if not daemon:
                continue
            scope = scope_of(node)
            if self._has_shutdown_path(scope):
                continue
            findings.append(self.finding(
                ctx, node,
                "daemon thread spawned with no stop event or join() in "
                "scope — it dies mid-operation at interpreter exit; add a "
                "threading.Event + bounded join() shutdown path"))
        return findings

    @staticmethod
    def _has_shutdown_path(scope: ast.AST) -> bool:
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                if _terminal(node.func) == "Event":
                    return True
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "join":
                    recv = node.func.value
                    if isinstance(recv, ast.Constant):
                        continue  # "sep".join(...) — string joining
                    dotted = _dotted(recv) or ""
                    if dotted.split(".")[-1] in ("path", "posixpath",
                                                 "ntpath", "os"):
                        continue  # os.path.join(...) — path joining
                    return True  # a thread/process join
        return False


# ---------------------------------------------------------------------------
# bare-print
# ---------------------------------------------------------------------------


class BarePrintRule(Rule):
    id = "bare-print"
    description = ("print() in library code — route output through "
                   "obs.logging (emit / get_logger)")

    def check(self, ctx: FileContext) -> List[Finding]:
        return [
            self.finding(ctx, node,
                         "bare print() in library code; use obs.logging's "
                         "emit() for CLI output or get_logger() for "
                         "diagnostics")
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Call) and
            isinstance(node.func, ast.Name) and node.func.id == "print"
        ]


# ---------------------------------------------------------------------------
# staleness-protocol
# ---------------------------------------------------------------------------


def _walk_same_scope(node: ast.AST):
    """Yield ``node`` and descendants WITHOUT descending into nested
    function/class/lambda bodies — a pull inside a nested def is not a
    pull on this scope's protocol timeline."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def _rpc_receiver(call: ast.Call, method: str) -> Optional[str]:
    """``client.pull(...)`` -> ``"client"`` (dotted receivers included:
    ``self._client.commit`` -> ``"self._client"``), else None."""
    if isinstance(call.func, ast.Attribute) and call.func.attr == method:
        return _dotted(call.func.value)
    return None


class StalenessProtocolRule(Rule):
    id = "staleness-protocol"
    description = ("commits built from a center pulled before the previous "
                   "commit's reply (a repeated commit with no fresh pull on "
                   "the same receiver)")

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_fn(ctx, node, findings)
        return findings

    def _check_fn(self, ctx: FileContext, fn: ast.AST,
                  findings: List[Finding]) -> None:
        # only receivers that PULL somewhere in this function follow the
        # pull/commit protocol; a commit-only stream (gradient push, no
        # center) is a different protocol, not a staleness bug
        pulled = set()
        for node in _walk_same_scope(fn):
            if isinstance(node, ast.Call):
                r = _rpc_receiver(node, "pull")
                if r:
                    pulled.add(r)
        if not pulled:
            return
        flagged: Set[int] = set()

        def flag(call: ast.Call, recv: str) -> None:
            if id(call) in flagged:
                return
            flagged.add(id(call))
            findings.append(self.finding(
                ctx, call,
                f"`{recv}.commit(...)` repeats without a fresh "
                f"`{recv}.pull()` since the previous commit — the delta "
                f"is built from a center pulled before the previous "
                f"commit's reply; pull at every window boundary"))

        def events_in(stmts) -> Tuple[Set[str], dict]:
            pulls: Set[str] = set()
            commits: dict = {}
            for stmt in stmts:
                for node in _walk_same_scope(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    r = _rpc_receiver(node, "pull")
                    if r in pulled:
                        pulls.add(r)
                    r = _rpc_receiver(node, "commit")
                    if r in pulled and r not in commits:
                        commits[r] = node
            return pulls, commits

        # state per receiver: None (no pull yet — protocol not started),
        # "fresh" (pulled since the last commit), "stale" (committed
        # since the last pull).  Exclusive branches (if/else, try
        # handlers) each run on a COPY and merge optimistically — fresh
        # beats None beats stale — so one commit per mutually exclusive
        # branch is never misread as a repeated commit.
        _RANK = {"fresh": 0, None: 1, "stale": 2}

        def merge(*branch_states: dict) -> dict:
            keys = set().union(*[set(s) for s in branch_states])
            return {k: min((s.get(k) for s in branch_states),
                           key=_RANK.__getitem__) for k in keys}

        def visit(stmts, state: dict) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    body = list(stmt.body) + list(stmt.orelse)
                    pulls_in, commits_in = events_in(body)
                    for recv, call in commits_in.items():
                        # a loop that commits but never pulls re-commits
                        # from whatever was pulled BEFORE the loop
                        if recv not in pulls_in and \
                                state.get(recv) is not None:
                            flag(call, recv)
                    visit(stmt.body, state)
                    visit(stmt.orelse, state)
                    continue
                if isinstance(stmt, ast.If):
                    branches = []
                    for body in (stmt.body, stmt.orelse):
                        b = dict(state)
                        visit(body, b)
                        branches.append(b)
                    state.clear()
                    state.update(merge(*branches))
                    continue
                if isinstance(stmt, ast.Try):
                    main = dict(state)
                    visit(list(stmt.body) + list(stmt.orelse), main)
                    paths = [main]
                    for h in stmt.handlers:  # exceptional alternates
                        hb = dict(state)
                        visit(h.body, hb)
                        paths.append(hb)
                    state.clear()
                    state.update(merge(*paths))
                    visit(stmt.finalbody, state)
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    visit(stmt.body, state)
                    continue
                # plain statement: protocol events in lexical order
                calls = [n for n in _walk_same_scope(stmt)
                         if isinstance(n, ast.Call)]
                calls.sort(key=lambda n: (n.lineno, n.col_offset))
                for call in calls:
                    r = _rpc_receiver(call, "pull")
                    if r in pulled:
                        state[r] = "fresh"
                        continue
                    r = _rpc_receiver(call, "commit")
                    if r in pulled:
                        if state.get(r) == "stale":
                            flag(call, r)
                        if state.get(r) is not None:
                            state[r] = "stale"

        visit(fn.body, {})


# ---------------------------------------------------------------------------
# shm-lifecycle
# ---------------------------------------------------------------------------


class ShmLifecycleRule(Rule):
    id = "shm-lifecycle"
    description = ("shared-memory segment created in a scope with no "
                   "unlink() on any shutdown path — the /dev/shm backing "
                   "outlives the process")

    @staticmethod
    def _creates_segment(call: ast.Call) -> bool:
        """``SharedMemory(create=True, ...)`` or ``ShmRing.create(...)``
        — the two ways this codebase mints a segment it then OWNS.
        ``SharedMemory(name=...)`` attachments are the peer side and
        must not unlink; they are never flagged."""
        if _terminal(call.func) == "SharedMemory":
            return any(kw.arg == "create" and
                       isinstance(kw.value, ast.Constant) and
                       kw.value.value is True for kw in call.keywords)
        return (_dotted(call.func) or "").endswith("ShmRing.create")

    def check(self, ctx: FileContext) -> List[Finding]:
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node

        def scope_of(node: ast.AST) -> ast.AST:
            """Nearest enclosing ClassDef, else the outermost
            FunctionDef, else the module — the region where the matching
            unlink for this segment would plausibly live (same rule as
            ``thread-shutdown``'s stop-path search)."""
            cur, outer_fn = node, None
            while id(cur) in parents:
                cur = parents[id(cur)]
                if isinstance(cur, ast.ClassDef):
                    return cur
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    outer_fn = cur
            return outer_fn if outer_fn is not None else ctx.tree

        findings = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    self._creates_segment(node)):
                continue
            if self._has_unlink_path(scope_of(node)):
                continue
            findings.append(self.finding(
                ctx, node,
                "shared-memory segment created with no unlink() in scope "
                "— close() only drops the mapping; without the creator's "
                "unlink() the /dev/shm backing leaks until reboot.  "
                "Unlink on the shutdown path (or pass unlink=True to the "
                "channel teardown)"))
        return findings

    @staticmethod
    def _has_unlink_path(scope: ast.AST) -> bool:
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "unlink":
                return True
            # delegated teardown: ShmChannel.close_rings(unlink=True)
            if any(kw.arg == "unlink" and
                   isinstance(kw.value, ast.Constant) and
                   kw.value.value is True for kw in node.keywords):
                return True
        return False


# ---------------------------------------------------------------------------
# wire-seam
# ---------------------------------------------------------------------------


class WireSeamRule(Rule):
    id = "wire-seam"
    description = ("raw socket recv()/sendall() outside ps/networking.py "
                   "— bypasses the zero-copy / fault-hook / byte-counter "
                   "wire seam")

    #: the methods that move bytes on a socket; attribute-call matching
    #: by name (the house style — bare-print, staleness-protocol), with
    #: the pragma as the escape hatch for a non-socket receiver
    _METHODS = ("recv", "recv_into", "sendall", "sendmsg")
    _SEAM = "ps/networking.py"

    def check(self, ctx: FileContext) -> List[Finding]:
        rel = ctx.rel.replace("\\", "/")
        if rel.endswith(self._SEAM) or rel == "networking.py":
            return []  # the seam itself is the one legitimate caller
        return [
            self.finding(
                ctx, node,
                f"raw socket .{node.func.attr}() outside ps/networking.py "
                "— every wire byte must travel the networking seam "
                "(v1/v2/shm/stream frame detection, the chaos fault "
                "hook, the net.* byte counters); use send_msg/recv_msg/"
                "send_packed/send_stream/recv_pull instead, or disable "
                "with a pragma if the receiver is not a socket")
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Call) and
            isinstance(node.func, ast.Attribute) and
            node.func.attr in self._METHODS
        ]


# ---------------------------------------------------------------------------
# kv-version-guard
# ---------------------------------------------------------------------------


class KvVersionGuardRule(Rule):
    id = "kv-version-guard"
    description = ("PrefixCache.insert_remote() outside serve/kvfabric.py "
                   "— bypasses the checkpoint-version-stamped fabric seam "
                   "and can serve KV computed under different weights")

    #: attribute-call matching by name, the wire-seam pattern: the cache
    #: object's spelling varies (self._prefix, engine._prefix, cache)
    #: but the method name is the seam's contract
    _METHODS = ("insert_remote",)
    _SEAM = "serve/kvfabric.py"

    def check(self, ctx: FileContext) -> List[Finding]:
        rel = ctx.rel.replace("\\", "/")
        if rel.endswith(self._SEAM) or rel == "kvfabric.py":
            return []  # the version-guarded seam is the one caller
        return [
            self.finding(
                ctx, node,
                "remote KV inserted outside serve/kvfabric.py — "
                "insert_remote() may only be called by the fabric's "
                "admit_remote_entry seam, which checks the checkpoint "
                "version stamp before the insert AND re-checks it after "
                "(a stale push is refused, never joined); an insert "
                "elsewhere can serve KV computed under different "
                "weights, or disable with a pragma if the receiver is "
                "not a PrefixCache")
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Call) and
            isinstance(node.func, ast.Attribute) and
            node.func.attr in self._METHODS
        ]


from .rules_project import PROJECT_RULES  # noqa: E402  (needs Rule above)

ALL_RULES: Tuple[Rule, ...] = (
    JitPurityRule(),
    LockDisciplineRule(),
    SwallowGuardRule(),
    ThreadShutdownRule(),
    BarePrintRule(),
    StalenessProtocolRule(),
    ShmLifecycleRule(),
    WireSeamRule(),
    KvVersionGuardRule(),
) + PROJECT_RULES

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in ALL_RULES}

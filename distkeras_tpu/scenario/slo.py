"""SLO-attainment accounting over the obs layer (ISSUE 17).

The serve fleet already measures everything the SLO verdict needs —
``serve.ttft_seconds`` / ``serve.e2e_seconds`` histograms, completion
and rejection counters — this module just reads them *per phase*.  The
runner cuts a cumulative registry snapshot at each phase boundary; a
:class:`PhaseAccountant` turns consecutive snapshots into interval
deltas (:func:`obs.snapshot_delta`, the same primitive the drift gate
uses) and computes per-phase:

* **attainment** — fraction of completed requests whose ttft AND e2e
  land within :class:`SLOTarget` (read exactly from histogram buckets:
  the default targets 0.25 s / 1.0 s sit ON ``TIME_BUCKETS`` bounds, so
  :func:`hist_fraction_le` is exact, not interpolated),
* **shed rate** — rejected / offered,
* **goodput** — tokens/sec counting only SLO-met requests (the
  runner's per-request verdicts feed ``scenario.goodput_tokens``; a
  phase that completes everything *late* scores zero goodput),
* p50/p99 ttft and e2e for the phase window.

Attainment from histograms instead of per-request logs is the point:
the verdict comes from the SAME instruments the drift gate watches, so
a bench snapshot's SLO claim and its drift gate can never disagree
about what happened.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Dict, List, Optional, Sequence

from ..obs import snapshot_delta, snapshot_quantile

#: histogram names the accountant reads from each interval
TTFT_HIST = "serve.ttft_seconds"
E2E_HIST = "serve.e2e_seconds"


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """A serving SLO: per-request latency bounds + the fleet-level
    attainment floor.  Defaults (250 ms ttft, 1 s e2e, 95 %) sit exactly
    on ``TIME_BUCKETS`` bounds — keep custom targets on bucket bounds
    too, or attainment silently becomes a lower bound (the fraction
    ≤ the next-lower bound) instead of exact."""

    ttft_s: float = 0.25
    e2e_s: float = 1.0
    attainment: float = 0.95

    def met(self, ttft_s: float, e2e_s: float) -> bool:
        """Per-request verdict (the runner's goodput classifier)."""
        return ttft_s <= self.ttft_s and e2e_s <= self.e2e_s


def hist_fraction_le(snap: Optional[dict], bound: float) -> Optional[float]:
    """Fraction of a histogram snapshot's observations ≤ ``bound``.
    Exact when ``bound`` is one of the histogram's bucket bounds
    (buckets hold per-bucket counts with le semantics: bucket i counts
    v in (bounds[i-1], bounds[i]]); otherwise the fraction up to the
    next-LOWER bound — a conservative lower bound on attainment, never
    an optimistic one.  ``None`` when there is nothing to read."""
    if not snap or snap.get("type") != "histogram" or not snap.get("count"):
        return None
    bounds = list(snap["bounds"])
    counts = list(snap["counts"])
    # bucket index i covers (bounds[i-1], bounds[i]]; everything in
    # buckets 0..k is <= bounds[k], so include bucket k iff
    # bounds[k] <= bound
    k = bisect.bisect_right(bounds, bound)
    return sum(counts[:k]) / snap["count"]


@dataclasses.dataclass(frozen=True)
class PhaseReport:
    """One phase's SLO verdict — plain data, rides the obs document
    (``row["phases"]``) and the obsview table."""

    phase: str
    offered: int           # dispatched into this phase window
    completed: int
    rejected: int          # load-shed (server said no)
    timeouts: int          # client deadline fired
    slo_met: int
    attainment: Optional[float]   # from the serve.* interval histograms
    shed_rate: float
    goodput_tps: float     # SLO-met tokens / phase wall seconds
    ttft_p50: Optional[float]
    ttft_p99: Optional[float]
    e2e_p50: Optional[float]
    e2e_p99: Optional[float]
    wall_s: float

    def to_row(self) -> dict:
        d = dataclasses.asdict(self)
        for k, v in d.items():
            if isinstance(v, float):
                d[k] = round(v, 6)
        return d

    def meets(self, target: SLOTarget) -> bool:
        """Phase-level verdict: attainment at or above the target floor.
        A phase with no completions fails — "nothing finished" is the
        worst attainment there is, not a free pass."""
        if self.attainment is None:
            return self.offered == 0
        return self.attainment >= target.attainment


class PhaseAccountant:
    """Turns the runner's phase-boundary registry snapshots + per-phase
    tallies into :class:`PhaseReport`s.

    Usage: ``cut(phase, snapshot, wall_s)`` once per boundary in phase
    order (the snapshot CLOSES the named phase; cumulative, as returned
    by ``Registry.snapshot()`` or the router's merged stats), after an
    initial ``open(snapshot)`` establishing the pre-traffic base."""

    def __init__(self, target: SLOTarget):
        self.target = target
        self._base: Optional[dict] = None
        self._reports: List[PhaseReport] = []

    def open(self, snapshot: dict) -> None:
        self._base = dict(snapshot)

    def cut(self, phase: str, snapshot: dict, wall_s: float,
            tallies: Dict[str, int]) -> PhaseReport:
        """Close ``phase`` with the cumulative ``snapshot`` taken at its
        end.  ``tallies`` carries the runner's client-side per-phase
        counts: offered / completed / rejected / timeouts / slo_met /
        goodput_tokens."""
        if self._base is None:
            raise RuntimeError("PhaseAccountant.cut before open")
        delta = snapshot_delta(self._base, snapshot)
        self._base = dict(snapshot)
        ttft = delta.get(TTFT_HIST)
        e2e = delta.get(E2E_HIST)
        frac_ttft = hist_fraction_le(ttft, self.target.ttft_s)
        frac_e2e = hist_fraction_le(e2e, self.target.e2e_s)
        # both bounds must hold; the fractions come from independent
        # histograms so the joint attainment is at best min(, ) — report
        # that (exact when misses are nested, conservative otherwise)
        attainment = None
        if frac_ttft is not None and frac_e2e is not None:
            attainment = min(frac_ttft, frac_e2e)
        elif frac_e2e is not None:
            attainment = frac_e2e
        elif frac_ttft is not None:
            attainment = frac_ttft
        offered = int(tallies.get("offered", 0))
        rejected = int(tallies.get("rejected", 0))
        timeouts = int(tallies.get("timeouts", 0))
        wall = max(float(wall_s), 1e-9)
        rep = PhaseReport(
            phase=phase, offered=offered,
            completed=int(tallies.get("completed", 0)),
            rejected=rejected, timeouts=timeouts,
            slo_met=int(tallies.get("slo_met", 0)),
            attainment=attainment,
            shed_rate=(rejected / offered) if offered else 0.0,
            goodput_tps=float(tallies.get("goodput_tokens", 0)) / wall,
            ttft_p50=_q(ttft, 0.5), ttft_p99=_q(ttft, 0.99),
            e2e_p50=_q(e2e, 0.5), e2e_p99=_q(e2e, 0.99),
            wall_s=float(wall_s))
        self._reports.append(rep)
        return rep

    @property
    def reports(self) -> Sequence[PhaseReport]:
        return tuple(self._reports)

    def misses(self) -> List[str]:
        """Phases trailing the attainment floor (obsview's SLO-MISS
        alarm reads this off the persisted rows)."""
        return [r.phase for r in self._reports if not r.meets(self.target)]


def _q(snap: Optional[dict], q: float) -> Optional[float]:
    if not snap or not snap.get("count"):
        return None
    return snapshot_quantile(snap, q)

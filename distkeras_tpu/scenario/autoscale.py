"""Obs-driven autoscaler (ISSUE 17): grow/shrink the engine fleet
behind ONE ``ServeRouter`` from the signals the obs layer already
publishes.

The scale primitive is the router's existing evict/rejoin machinery —
nothing new to trust: scale-DOWN is the planned single-engine drain
(migrate hot KV to survivors → drain → evict; ``router.scale_down``),
scale-UP un-drains a parked engine and re-adopts it through the same
stats-probe path a rejoining engine takes (``router.scale_up``).  A
parked engine keeps its warm-compiled model, so scale-up costs a
round-trip, not a recompile — ``jit.retraces`` stays 0 across the
whole scaling history.

The policy is deliberately boring — thresholds with hysteresis:

* **pressure** (scale up): fleet queue depth per live engine at or
  above ``queue_high``, OR interval SLO attainment below
  ``attainment_low``;
* **slack** (scale down): queue per engine at or below ``queue_low``
  AND attainment at or above ``attainment_high`` (or no traffic).

A decision fires only after the signal holds for ``up_after`` /
``down_after`` consecutive ticks AND the post-action ``cooldown_s`` has
elapsed — the anti-flap pair.  Both streaks reset after any action, so
the scaler re-observes the NEW fleet before moving again.  Every
decision is a ``scenario.scale_{up,down}`` counter increment plus a
JSONL ``scale_event`` record — the audit trail ``obsview --scenario``
renders.

:meth:`AutoScaler.decide` is a pure function of (signals, now, its own
streak/cooldown state) and is unit-tested against synthetic noisy
signals without any fleet; the thread loop just feeds it real signals
from the router's merged stats.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from ..obs import Registry, default_registry, snapshot_delta
from ..obs.logging import get_logger
from ..utils.metrics import MetricsLogger
from .slo import E2E_HIST, TTFT_HIST, SLOTarget, hist_fraction_le

_LOG = "scenario.autoscale"


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Scaling knobs.  ``queue_*`` are per-LIVE-engine queue depths
    (fleet total / engines alive), ``attainment_*`` the interval SLO
    attainment bounds, ``*_after`` consecutive-tick streak lengths, and
    ``cooldown_s`` the refractory period after any action."""

    min_engines: int = 1
    max_engines: int = 4
    interval_s: float = 0.25
    queue_high: float = 4.0
    queue_low: float = 0.5
    attainment_low: float = 0.90
    attainment_high: float = 0.98
    up_after: int = 2
    down_after: int = 6
    cooldown_s: float = 1.0
    #: completions needed in an interval before its attainment counts —
    #: two requests can't outvote the queue signal
    min_samples: int = 8


@dataclasses.dataclass(frozen=True)
class Signals:
    """One tick's inputs: live engines, fleet queue depth, and interval
    attainment (``None`` = not enough samples — no opinion)."""

    alive: int
    queue_depth: float
    attainment: Optional[float]


class AutoScaler:
    """Poll → decide → act loop over a ``ServeRouter``.

    ``router`` needs ``scale_up(addr)`` / ``scale_down(addr)`` and the
    ``backends`` list (addr/alive/idx) — i.e. a ``ServeRouter``.  Call
    :meth:`start` / :meth:`stop` around the traffic window, or drive
    :meth:`tick` manually from a test."""

    def __init__(self, router, policy: Optional[AutoscalePolicy] = None,
                 *, target: Optional[SLOTarget] = None,
                 registry: Optional[Registry] = None,
                 events: Optional[MetricsLogger] = None,
                 alerts=None):
        self.router = router
        #: optional :class:`~distkeras_tpu.obs.alerts.AlertEngine`
        #: (ISSUE 20): when set, each tick evaluates it and prefers its
        #: burn-rate attainment (computed over the router's PUSH-fed
        #: aggregator windows) to this scaler's own two-poll delta math —
        #: one SLO computation shared by alerts and scaling decisions
        self.alerts = alerts
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.target = target if target is not None else SLOTarget()
        self.registry = registry if registry is not None \
            else default_registry()
        self.events = events
        self.log = get_logger(_LOG)
        self._c_up = self.registry.counter("scenario.scale_up")
        self._c_down = self.registry.counter("scenario.scale_down")
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown_until = 0.0
        self._last_stats: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: decision history [(t_rel, action, alive_after, reason)] —
        #: the scale-event trail the scenario row persists
        self.history: List[dict] = []
        self._t0 = time.perf_counter()

    # -- decision (pure w.r.t. the fleet: unit-testable) --------------------
    def decide(self, signals: Signals, now: float) -> Optional[str]:
        """Fold one tick of signals through the hysteresis state;
        returns ``"up"`` / ``"down"`` / ``None``.  Mutates only streaks
        and cooldown — never the fleet (that's :meth:`tick`)."""
        p = self.policy
        per_engine = signals.queue_depth / max(signals.alive, 1)
        att = signals.attainment
        pressure = (per_engine >= p.queue_high
                    or (att is not None and att < p.attainment_low))
        slack = (per_engine <= p.queue_low
                 and (att is None or att >= p.attainment_high))
        self._up_streak = self._up_streak + 1 if pressure else 0
        self._down_streak = self._down_streak + 1 if slack else 0
        if now < self._cooldown_until:
            return None
        if (self._up_streak >= p.up_after
                and signals.alive < p.max_engines):
            self._arm(now)
            return "up"
        if (self._down_streak >= p.down_after
                and signals.alive > p.min_engines):
            self._arm(now)
            return "down"
        return None

    def _arm(self, now: float) -> None:
        self._up_streak = self._down_streak = 0
        self._cooldown_until = now + self.policy.cooldown_s

    # -- signal gathering ---------------------------------------------------
    def read_signals(self) -> Signals:
        """One merged-stats poll → a :class:`Signals`.  Attainment is
        the min of the interval ttft/e2e fractions between THIS poll
        and the previous one (the same read the phase accountant does,
        at tick granularity)."""
        reply = self.router._handle_stats()
        stats = reply.get("stats", {}) or {}
        att = None
        if self.alerts is not None:
            self.alerts.evaluate()
            att = self.alerts.attainment_signal()
        if att is None and self._last_stats is not None:
            delta = snapshot_delta(self._last_stats, stats)
            e2e = delta.get(E2E_HIST)
            if e2e and e2e.get("count", 0) >= self.policy.min_samples:
                fr_e2e = hist_fraction_le(e2e, self.target.e2e_s)
                fr_ttft = hist_fraction_le(delta.get(TTFT_HIST),
                                           self.target.ttft_s)
                cands = [f for f in (fr_e2e, fr_ttft) if f is not None]
                att = min(cands) if cands else None
        self._last_stats = stats
        return Signals(alive=int(reply.get("engines_alive", 0)),
                       queue_depth=float(reply.get("queue_depth", 0) or 0),
                       attainment=att)

    # -- action -------------------------------------------------------------
    def tick(self) -> Optional[str]:
        """One poll-decide-act cycle; returns the action taken."""
        signals = self.read_signals()
        now = time.perf_counter()
        action = self.decide(signals, now)
        if action is None:
            return None
        if action == "up":
            be = next((b for b in self.router.backends if not b.alive),
                      None)
            if be is None:
                return None
            result = self.router.scale_up(be.addr)
        else:
            parked = [b for b in self.router.backends if b.alive]
            if len(parked) <= self.policy.min_engines:
                return None
            be = parked[-1]
            result = self.router.scale_down(be.addr)
        ok = bool(result.get("ok"))
        if ok:
            (self._c_up if action == "up" else self._c_down).inc()
        alive = sum(b.alive for b in self.router.backends)
        reason = (f"queue/engine={signals.queue_depth / max(signals.alive, 1):.1f}"
                  f" attainment="
                  f"{'n/a' if signals.attainment is None else f'{signals.attainment:.3f}'}")
        event = {"t": round(now - self._t0, 3), "action": action,
                 "engine": be.addr, "ok": ok, "alive": alive,
                 "reason": reason}
        self.history.append(event)
        self.log.info("scale_%s %s (alive=%d, %s)%s", action, be.addr,
                      alive, reason, "" if ok else " FAILED")
        if self.events is not None:
            self.events.log("scale_event", **event)
        return action if ok else None

    # -- thread loop --------------------------------------------------------
    def start(self) -> "AutoScaler":
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._t0 = time.perf_counter()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.policy.interval_s):
            try:
                self.tick()
            except Exception as e:           # noqa: BLE001 — keep polling
                self.log.warning("autoscaler tick failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def summary(self) -> Dict[str, object]:
        return {"scale_up": int(self._c_up.value),
                "scale_down": int(self._c_down.value),
                "events": list(self.history)}

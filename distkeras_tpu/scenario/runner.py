"""Open-loop scenario runner (ISSUE 17): fire a :class:`ScenarioSpec`
at the serve fleet AT ITS TRACE TIMESTAMPS, regardless of completion.

The closed-loop benches adapt their offered load to the service —
a slow fleet quietly sheds its own traffic.  This runner does not: a
dispatcher thread walks the arrival schedule on the wall clock and
hands each request to a worker pool the moment its timestamp comes due.
If every worker is busy the request *waits dispatched*, and the wait is
recorded as ``scenario.dispatch_skew_seconds`` (measured worker-side,
start-minus-scheduled) — generator lag is visible in its own histogram
and can never masquerade as server latency.

Accounting is exact by construction: every dispatched request ends in
exactly one of three ways —

* **completed** — the server replied ``ok`` (SLO verdict + goodput
  tokens recorded from the server-measured ttft/e2e in the reply),
* **rejected** — the admission controller load-shed it (or the request
  errored server-side),
* **timeouts** — the client-side deadline fired (the socket is poisoned
  mid-reply, so the worker replaces its connection), or the connection
  died — either way the CLIENT gave up.

and ``scenario.dispatched == completed + rejected + timeouts`` is
asserted at drain (:meth:`ScenarioRunner.run` raises on mismatch).
Phase attribution is by ARRIVAL time (the phase a request belonged to
when it was offered), while the interval registry snapshots cut at the
phase-boundary wall times attribute server-side histograms by
COMPLETION time — both views ride the persisted row.

Chaos hook: :meth:`mark_eviction` stamps "an engine just died"; the
next completed request (on any worker — i.e. served by a survivor)
closes the window into ``scenario.recovery_seconds``.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..obs import TIME_BUCKETS, Registry, default_registry
from ..obs.logging import get_logger
from ..utils.metrics import MetricsLogger
from .slo import PhaseAccountant, SLOTarget
from .traces import ScenarioSpec

_LOG = "scenario.runner"

#: every instrument the runner touches, pre-created before traffic (the
#: PR 7 convention) so a scenario that never sheds/times out/recovers
#: shows 0 — present-not-missing — in drift diffs
SCENARIO_COUNTERS = (
    "scenario.dispatched", "scenario.completed", "scenario.rejected",
    "scenario.timeouts", "scenario.slo_met", "scenario.slo_missed",
    "scenario.goodput_tokens", "scenario.scale_up", "scenario.scale_down",
)
SCENARIO_HISTOGRAMS = (
    "scenario.dispatch_skew_seconds", "scenario.recovery_seconds",
)


def precreate_metrics(registry: Optional[Registry] = None) -> Registry:
    """Materialize every ``scenario.*`` counter/histogram at 0."""
    reg = registry if registry is not None else default_registry()
    for name in SCENARIO_COUNTERS:
        reg.counter(name)
    for name in SCENARIO_HISTOGRAMS:
        reg.histogram(name, TIME_BUCKETS)
    return reg


def _blank_tally() -> Dict[str, int]:
    return {"offered": 0, "completed": 0, "rejected": 0, "timeouts": 0,
            "slo_met": 0, "goodput_tokens": 0}


def build_prompt(arrival, idx: int, vocab: int,
                 prefix_len: int = 8) -> np.ndarray:
    """Deterministic prompt tokens for one arrival: requests of the
    same ``group`` share their first ``prefix_len`` tokens (the shared
    system prompt the affinity router and KV cache key on), the rest is
    unique per request index.  Pure function of (arrival, idx, vocab,
    prefix_len) — replaying a trace replays the exact token streams."""
    n = int(arrival.prompt_len)
    if arrival.group >= 0 and n > 1:
        p = min(int(prefix_len), n - 1)
        head = np.random.default_rng(1_000_003 + arrival.group) \
            .integers(0, vocab, size=p)
        tail = np.random.default_rng(7_000_003 + idx) \
            .integers(0, vocab, size=n - p)
        toks = np.concatenate([head, tail])
    else:
        toks = np.random.default_rng(7_000_003 + idx) \
            .integers(0, vocab, size=n)
    return toks.astype(np.int32)


class ScenarioRunner:
    """Drive one :class:`ScenarioSpec` through a pool of workers, each
    owning its own client to the fleet front door.

    ``make_client`` returns a fresh connected client (``ServeClient``
    to the router) — called once per worker plus once per poisoned
    connection.  ``snap`` returns the CUMULATIVE fleet snapshot the
    phase accountant diffs (``client.stats()["stats"]`` against the
    router merges every live engine); when ``None`` the per-phase
    server-side view is skipped and only client-side tallies report.
    """

    def __init__(self, spec: ScenarioSpec, make_client: Callable[[], object],
                 *, snap: Optional[Callable[[], dict]] = None,
                 registry: Optional[Registry] = None,
                 target: Optional[SLOTarget] = None,
                 workers: int = 8, deadline_s: Optional[float] = None,
                 vocab: int = 64, prefix_len: int = 8,
                 events: Optional[MetricsLogger] = None):
        self.spec = spec
        self.make_client = make_client
        self.snap = snap
        self.registry = precreate_metrics(registry)
        self.target = target if target is not None else SLOTarget()
        self.workers = max(1, int(workers))
        self.deadline_s = deadline_s
        self.vocab = int(vocab)
        self.prefix_len = int(prefix_len)
        self.events = events
        self.log = get_logger(_LOG)

        r = self.registry
        self._c_dispatched = r.counter("scenario.dispatched")
        self._c_completed = r.counter("scenario.completed")
        self._c_rejected = r.counter("scenario.rejected")
        self._c_timeouts = r.counter("scenario.timeouts")
        self._c_slo_met = r.counter("scenario.slo_met")
        self._c_slo_missed = r.counter("scenario.slo_missed")
        self._c_goodput = r.counter("scenario.goodput_tokens")
        self._h_skew = r.histogram("scenario.dispatch_skew_seconds",
                                   TIME_BUCKETS)
        self._h_recovery = r.histogram("scenario.recovery_seconds",
                                       TIME_BUCKETS)

        self._q: "queue.Queue" = queue.Queue()
        self._tallies: List[Dict[str, Dict[str, int]]] = [
            {} for _ in range(self.workers)]
        self._evict_lock = threading.Lock()
        self._evict_t: Optional[float] = None
        self._recoveries = 0

    # -- chaos hook ---------------------------------------------------------
    def mark_eviction(self, t: Optional[float] = None) -> None:
        """Stamp "an engine just died" — the next completion (served by
        a survivor, by definition) closes the recovery window into
        ``scenario.recovery_seconds``.  Re-marking before recovery
        keeps the EARLIER stamp: recovery is measured from the first
        casualty of the incident."""
        with self._evict_lock:
            if self._evict_t is None:
                self._evict_t = time.perf_counter() if t is None else t

    def _note_completion(self) -> None:
        with self._evict_lock:
            if self._evict_t is not None:
                dt = time.perf_counter() - self._evict_t
                self._evict_t = None
                self._recoveries += 1
            else:
                return
        self._h_recovery.observe(max(dt, 0.0))
        self.log.info("recovered %.3fs after eviction", dt)
        if self.events is not None:
            self.events.log("recovery", seconds=round(dt, 6))

    # -- worker side --------------------------------------------------------
    def _fresh_client(self):
        try:
            return self.make_client()
        except (ConnectionError, OSError) as e:
            self.log.warning("client (re)dial failed: %s", e)
            return None

    def _worker(self, wid: int) -> None:
        client = self._fresh_client()
        tallies = self._tallies[wid]
        while True:
            item = self._q.get()
            if item is None:
                break
            arrival, sched, idx = item
            start = time.perf_counter()
            self._h_skew.observe(max(0.0, start - sched))
            tally = tallies.setdefault(arrival.phase, _blank_tally())
            tally["offered"] += 1
            self._c_dispatched.inc()
            if client is None:
                client = self._fresh_client()
            if client is None:
                # front door unreachable — the CLIENT gives up, which is
                # the timeout outcome (keeps the 3-way invariant exact)
                self._c_timeouts.inc()
                tally["timeouts"] += 1
                continue
            prompt = build_prompt(arrival, idx, self.vocab,
                                  self.prefix_len)
            try:
                if self.deadline_s is not None:
                    client.sock.settimeout(self.deadline_s)
                reply = client.generate(
                    prompt, max_new_tokens=arrival.new_tokens)
                if self.deadline_s is not None:
                    client.sock.settimeout(
                        getattr(client, "connect_timeout", 30.0))
            except socket.timeout:
                # deadline fired mid-reply: the connection is poisoned
                # (a late reply would desynchronize the framing) —
                # replace it
                self._c_timeouts.inc()
                tally["timeouts"] += 1
                try:
                    client.sock.close()
                except OSError:
                    pass
                client = self._fresh_client()
                continue
            except (ConnectionError, OSError):
                self._c_timeouts.inc()
                tally["timeouts"] += 1
                try:
                    client.sock.close()
                except OSError:
                    pass
                client = self._fresh_client()
                continue
            if reply.get("ok"):
                self._c_completed.inc()
                tally["completed"] += 1
                ttft = float(reply.get("ttft_s") or 0.0)
                e2e = float(reply.get("e2e_s") or 0.0)
                ntok = int(np.size(reply.get("tokens", ())))
                if self.target.met(ttft, e2e):
                    self._c_slo_met.inc()
                    self._c_goodput.inc(ntok)
                    tally["slo_met"] += 1
                    tally["goodput_tokens"] += ntok
                else:
                    self._c_slo_missed.inc()
                self._note_completion()
            else:
                # load-shed ("rejected") and malformed-request errors
                # both mean the SERVER refused it — the shed bucket
                self._c_rejected.inc()
                tally["rejected"] += 1
        if client is not None:
            try:
                client.close()
            except (ConnectionError, OSError):
                pass

    # -- dispatcher ---------------------------------------------------------
    def run(self) -> dict:
        """Fire the whole trace, drain, account.  Returns the scenario
        row: per-phase reports, totals, the exact-accounting proof, and
        recovery stats.  Raises ``RuntimeError`` if the open-loop
        invariant breaks."""
        spec = self.spec
        acct = PhaseAccountant(self.target)
        threads = [threading.Thread(target=self._worker, args=(w,),
                                    name=f"scn-worker-{w}", daemon=True)
                   for w in range(self.workers)]
        for t in threads:
            t.start()
        cuts: List[tuple] = []          # (phase, snapshot, wall_s)
        t0 = time.perf_counter()
        if self.snap is not None:
            acct.open(self.snap())
        self.log.info("scenario %s: %d arrivals, %d workers, phases %s",
                      spec.name, len(spec.arrivals), self.workers,
                      "/".join(spec.phases))
        if self.events is not None:
            self.events.log("scenario_start", name=spec.name,
                            seed=spec.seed, arrivals=len(spec.arrivals),
                            workers=self.workers)
        # phase boundaries AFTER the first (which opens at 0)
        bounds = [(p, s) for p, s in spec.phase_bounds]
        bi = 1
        prev_cut_t = 0.0

        def _cut_through(now_rel: float):
            # close every phase whose window ended at or before now_rel
            nonlocal bi, prev_cut_t
            while bi < len(bounds) and bounds[bi][1] <= now_rel:
                phase, start = bounds[bi - 1][0], bounds[bi][1]
                _sleep_until(t0 + start)
                snap = self.snap() if self.snap is not None else None
                cuts.append((phase, snap, start - prev_cut_t))
                prev_cut_t = start
                bi += 1

        for idx, a in enumerate(spec.arrivals):
            _cut_through(a.t)
            _sleep_until(t0 + a.t)
            self._q.put((a, t0 + a.t, idx))
        # phases with no arrivals left on the clock still get their cuts
        _cut_through(spec.duration_s + 1e-9)
        # drain: all arrivals are in flight or queued — sentinels stop
        # the workers once the queue empties
        for _ in threads:
            self._q.put(None)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        final_snap = self.snap() if self.snap is not None else None
        cuts.append((bounds[-1][0], final_snap, wall - prev_cut_t))

        tallies = self._merge_tallies()
        reports = []
        for phase, snap, wall_s in cuts:
            if self.snap is not None:
                rep = acct.cut(phase, snap, wall_s,
                               tallies.get(phase, _blank_tally()))
            else:
                rep = acct_offline(acct, phase, wall_s,
                                   tallies.get(phase, _blank_tally()))
            reports.append(rep)
            if self.events is not None:
                self.events.log("phase_report", **rep.to_row())

        counts = {k: int(self.registry.counter(f"scenario.{k}").value)
                  for k in ("dispatched", "completed", "rejected",
                            "timeouts", "slo_met", "goodput_tokens")}
        settled = (counts["completed"] + counts["rejected"]
                   + counts["timeouts"])
        if counts["dispatched"] != settled:
            raise RuntimeError(
                f"open-loop accounting broken: dispatched="
                f"{counts['dispatched']} != completed+rejected+timeouts="
                f"{settled}")
        if counts["dispatched"] != len(spec.arrivals):
            raise RuntimeError(
                f"dispatch loss: {counts['dispatched']} dispatched of "
                f"{len(spec.arrivals)} scheduled")
        row = {
            "scenario": spec.name, "seed": spec.seed,
            "arrivals": len(spec.arrivals), "wall_s": round(wall, 3),
            "phases": [r.to_row() for r in reports],
            "slo": {"ttft_s": self.target.ttft_s,
                    "e2e_s": self.target.e2e_s,
                    "attainment": self.target.attainment},
            "slo_misses": acct.misses(),
            "counts": counts,
            "accounting_exact": True,
            "recoveries": self._recoveries,
        }
        if self.events is not None:
            self.events.log("scenario_done", name=spec.name,
                            wall_s=round(wall, 3), **counts)
        self.log.info(
            "scenario %s done: %d/%d completed, %d shed, %d timeouts, "
            "misses=%s", spec.name, counts["completed"],
            counts["dispatched"], counts["rejected"], counts["timeouts"],
            row["slo_misses"] or "none")
        return row

    def _merge_tallies(self) -> Dict[str, Dict[str, int]]:
        merged: Dict[str, Dict[str, int]] = {}
        for per_worker in self._tallies:
            for phase, t in per_worker.items():
                m = merged.setdefault(phase, _blank_tally())
                for k, v in t.items():
                    m[k] += v
        return merged


def acct_offline(acct: PhaseAccountant, phase: str, wall_s: float,
                 tallies: Dict[str, int]):
    """Client-tallies-only phase report for runs without a ``snap``
    source (no server-side histograms ⇒ no attainment/percentiles)."""
    from .slo import PhaseReport
    offered = int(tallies.get("offered", 0))
    rejected = int(tallies.get("rejected", 0))
    wall = max(float(wall_s), 1e-9)
    rep = PhaseReport(
        phase=phase, offered=offered,
        completed=int(tallies.get("completed", 0)),
        rejected=rejected, timeouts=int(tallies.get("timeouts", 0)),
        slo_met=int(tallies.get("slo_met", 0)), attainment=None,
        shed_rate=(rejected / offered) if offered else 0.0,
        goodput_tps=float(tallies.get("goodput_tokens", 0)) / wall,
        ttft_p50=None, ttft_p99=None, e2e_p50=None, e2e_p99=None,
        wall_s=float(wall_s))
    acct._reports.append(rep)
    return rep


def _sleep_until(deadline: float) -> None:
    while True:
        dt = deadline - time.perf_counter()
        if dt <= 0:
            return
        time.sleep(min(dt, 0.05))

"""Scenario harness (ISSUE 17): trace-driven open-loop load, SLO
attainment accounting, and the obs-driven autoscaler.

``traces`` generates seeded deterministic arrival schedules at
production shape (Poisson / diurnal / flash-crowd, heavy-tail lengths,
shared-prefix mix) or replays recorded JSONL traces; ``runner`` fires
them open-loop at the serve fleet with exact three-way accounting;
``slo`` turns the fleet's own ``serve.*`` histograms into per-phase
attainment/shed/goodput verdicts; ``autoscale`` grows and shrinks
engines behind the router from those same signals.  One entry point:
``bench.py --scenario NAME``.
"""

from .autoscale import AutoscalePolicy, AutoScaler, Signals
from .runner import (SCENARIO_COUNTERS, SCENARIO_HISTOGRAMS,
                     ScenarioRunner, build_prompt, precreate_metrics)
from .slo import (PhaseAccountant, PhaseReport, SLOTarget,
                  hist_fraction_le)
from .traces import (Arrival, LengthModel, PrefixMix, ScenarioSpec,
                     diurnal_trace, poisson_trace, replay_trace,
                     save_trace, spike_trace)

__all__ = [
    "Arrival", "AutoScaler", "AutoscalePolicy", "LengthModel",
    "PhaseAccountant", "PhaseReport", "PrefixMix", "SCENARIO_COUNTERS",
    "SCENARIO_HISTOGRAMS", "SLOTarget", "ScenarioRunner", "ScenarioSpec",
    "Signals", "build_prompt", "diurnal_trace", "hist_fraction_le",
    "poisson_trace", "precreate_metrics", "replay_trace", "save_trace",
    "spike_trace",
]

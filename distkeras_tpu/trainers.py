"""Training orchestration — the dist-keras trainer API, TPU-native.

Parity surface with reference ``distkeras/trainers.py``: the same class
names (``SingleTrainer``, ``AveragingTrainer``, ``EnsembleTrainer``,
``DOWNPOUR``, ``AEASGD``, ``EAMSGD``, ``DynSGD``, ``ADAG``), the same
hyperparameters (``num_workers``, ``batch_size``, ``communication_window``,
``rho``, ``momentum``, ``num_epoch``, ``features_col``, ``label_col``) and
the same contract: ``trainer.train(dataset) -> trained model``, plus
``get_training_time()`` / ``get_history()`` / ``serialize()``.

Under the hood nothing resembles the reference's Spark + socket-PS stack:

* ``mode="sync"`` (default): the algorithm's synchronous limit as one
  jit-compiled SPMD program over a ``jax.sharding.Mesh`` — local window
  scans + psum/pmean at window edges (``distkeras_tpu.parallel.sync``).
  This is the idiomatic, fast path: collectives ride ICI, chips never wait
  on a host.
* ``mode="async"``: faithful asynchronous semantics (true staleness, shared
  center variable, per-commit update rules) via the host-side parameter
  server (``distkeras_tpu.ps``) — the reference's behavioral twin.
"""

from __future__ import annotations

import os
import time
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .data.dataset import Dataset
from .models.layers import Activation, Dense, Sequential
from .models.model import Model
from .obs import SpanTracer
from .obs import profile as obs_profile
from .obs.registry import default_registry
from .ops.losses import get_loss, probs_loss_variant
from .ops.optimizers import get_optimizer
from .parallel import mesh as mesh_lib
from .parallel.sync import (AdagSync, DownpourSync, DynSgdSync, EasgdSync,
                            NoCommSync, SyncEngine, make_window_fn, tmap)
from .utils import serde
from .utils.checkpoint import CheckpointManager
from .utils.metrics import MetricsLogger


class _EpochPipeline:
    """Deferred per-epoch loss readback.

    The reference's workers accumulate loss history on the host as they go;
    a naive translation (``np.asarray(losses)`` after every epoch) inserts a
    device→host sync per epoch and drains the TPU dispatch queue — measured
    ~27% of headline throughput (VERDICT round 2).  Instead, epoch k's
    (on-device) losses are fetched only AFTER epoch k+1 has been
    dispatched, so the readback overlaps device compute and the queue never
    empties.  ``flush()`` performs the final hard sync before the trainer
    returns — timing stays honest: each epoch's wall time is marked at the
    completion of its loss readback, so ``sum(epoch_seconds)`` spans loop
    start → last epoch's compute actually finished.
    """

    def __init__(self, trainer: "Trainer", samples: int, reshape=None):
        self.trainer = trainer
        self.samples = samples
        self.reshape = reshape
        self.pending = None
        self.t_mark = time.time()

    def push(self, epoch: int, dev_losses) -> None:
        """Hand over epoch's device losses; drains the previous epoch."""
        prev, self.pending = self.pending, (epoch, dev_losses)
        self._drain(prev)

    def flush(self) -> None:
        self._drain(self.pending)
        self.pending = None

    def _drain(self, item) -> None:
        if item is None:
            return
        epoch, dev_losses = item
        losses = _to_host(dev_losses)  # waits for that epoch's compute
        if self.reshape is not None:
            losses = losses.reshape(self.reshape)
        now = time.time()
        dt, self.t_mark = now - self.t_mark, now
        self.trainer.history.append(losses)
        self.trainer._epoch_metrics(epoch, losses, dt, self.samples)


def _to_host(x):
    """Device leaf → host numpy; on a multi-HOST mesh (jax.distributed)
    allgather the shards this process cannot address so every process
    returns the same complete trained model (the async cluster's
    broadcast contract, for the GSPMD/pipeline trainers)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def _resolve_dtype(dtype):
    """None | str | dtype -> numpy dtype (or None).  Accepts the common
    shorthands so ``compute_dtype="bf16"`` works."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        dtype = {"bf16": "bfloat16", "fp16": "float16",
                 "f32": "float32", "fp32": "float32"}.get(dtype, dtype)
    return jnp.dtype(dtype)


def _ends_in_prob_activation(model) -> bool:
    """Reference models end in a softmax (or sigmoid, for binary heads)
    layer and train with crossentropy on probabilities (Keras semantics).
    Detect that so the loss can use the numerically-stable on-probs
    variant.  Works for native models and ingested Keras-3 models."""
    kmodel = getattr(model, "keras_model", None)
    if kmodel is not None:
        try:
            last = kmodel.layers[-1]
            if type(last).__name__ in ("Softmax", "Sigmoid"):
                return True
            act = getattr(last, "activation", None)
            return getattr(act, "__name__", None) in ("softmax", "sigmoid")
        except (IndexError, AttributeError):
            return False
    layer = model.layer
    while isinstance(layer, Sequential) and layer.layers:
        layer = layer.layers[-1]
    if isinstance(layer, (Activation, Dense)) and \
            layer.activation in ("softmax", "sigmoid"):
        return True
    return False


class Trainer:
    """Base trainer (reference ``distkeras/trainers.py:Trainer``): owns the
    model + optimizer + loss, records wall-clock training time and the
    per-iteration loss history."""

    def __init__(self, keras_model: Model, worker_optimizer="sgd",
                 loss="categorical_crossentropy", features_col: str = "features",
                 label_col: str = "label", num_epoch: int = 1,
                 batch_size: int = 32, learning_rate: float = 0.01,
                 seed: int = 0, checkpoint_dir: Optional[str] = None,
                 checkpoint_keep: int = 3, metrics=None,
                 compute_dtype=None, remat: bool = False,
                 aux_weight: float = 0.0, profile=None):
        self.model = keras_model
        self.worker_optimizer = worker_optimizer
        self.loss = loss
        self.features_col = features_col
        self.label_col = label_col
        self.num_epoch = int(num_epoch)
        self.batch_size = int(batch_size)
        self.learning_rate = float(learning_rate)
        self.seed = int(seed)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_keep = int(checkpoint_keep)
        #: mixed precision: cast activations to this dtype in the train
        #: step (params/optimizer state stay f32 — layers cast weights to
        #: the activation dtype at use, so matmuls/convs hit the MXU in
        #: e.g. bfloat16 while the master copy keeps full precision).
        self.compute_dtype = _resolve_dtype(compute_dtype)
        #: rematerialization (jax.checkpoint around the forward): trade
        #: recompute FLOPs for activation HBM — for deep models whose
        #: activations, not weights, are what OOMs (SURVEY.md §7 /
        #: scaling-book memory recipe)
        self.remat = bool(remat)
        #: opt-in MoE router load-balance weight: folds
        #: ``aux_weight * Σ state['aux_loss']`` into the objective
        #: (ADVICE r3 — mitigates router/expert collapse; 0.0 keeps the
        #: reference-parity task-loss-only behavior)
        self.aux_weight = float(aux_weight)
        if metrics is None or isinstance(metrics, MetricsLogger):
            self.metrics = metrics or MetricsLogger(None)
        else:
            self.metrics = MetricsLogger(metrics)
        #: span tracer bound to the SAME sink as the metrics — traces and
        #: per-epoch records interleave in one JSONL stream (ISSUE 2),
        #: readable by ``scripts/obsview.py``
        self.tracer = SpanTracer(self.metrics)
        #: profiling knobs (ISSUE 6): per-epoch ``jax.profiler`` captures,
        #: the block_until_ready step-time split, memory watermarks —
        #: ``obs.ProfileConfig`` | dict of its fields | trace-dir string
        self.profile = obs_profile.ProfileConfig.resolve(profile)
        #: per-(kind, config) retrace sentinels behind ``_instrumented``:
        #: the cold/warm split for the ``jit_compile`` span AND the
        #: ``jit.compiles``/``jit.retraces`` counters (ISSUE 6)
        self._sentinels: dict = {}

        self.history: list = []
        self.training_time: float = 0.0
        self.trained_variables: Optional[dict] = None

    # -- parity helpers -----------------------------------------------------
    def get_training_time(self) -> float:
        """Parity: reference ``Trainer.get_training_time``."""
        return self.training_time

    def get_history(self) -> list:
        """Per-epoch arrays of per-iteration training loss (reference
        workers accumulate these and trainers expose them)."""
        return self.history

    def get_averaged_history(self) -> np.ndarray:
        """Mean loss per epoch (reference history-averaging helpers in
        ``distkeras/utils.py``)."""
        return np.array([float(np.mean(h)) for h in self.history])

    def serialize(self) -> bytes:
        """Parity: reference ``Trainer.serialize`` (pickled model blob) —
        ours is the msgpack model+variables blob."""
        return serde.serialize_model(self.model, self.trained_variables)

    # -- shared plumbing ----------------------------------------------------
    def _resolve(self):
        loss_fn = get_loss(self.loss)
        if isinstance(self.loss, str) and _ends_in_prob_activation(self.model):
            loss_fn = probs_loss_variant(self.loss) or loss_fn
        optimizer = get_optimizer(self.worker_optimizer, self.learning_rate)
        return loss_fn, optimizer

    def _config_key(self) -> tuple:
        """Hashable fingerprint of everything the compiled programs capture;
        the caches below rebuild when it changes, so mutating a trainer
        hyperparameter between ``train()`` calls takes effect."""
        o, l = self.worker_optimizer, self.loss
        return (o if isinstance(o, str) else id(o),
                l if isinstance(l, str) else id(l),
                self.learning_rate, str(self.compute_dtype), self.remat,
                self.aux_weight)

    def _obs_registry(self):
        """Where this trainer's profiled metrics land: the tracer's
        registry when one is attached (bench.py scopes a private one),
        else the process-wide default."""
        return self.tracer.registry if self.tracer.registry is not None \
            else default_registry()

    def _instrumented(self, run, kind: str = "window"):
        """Split first-call compile time from steady-state dispatch: the
        first invocation of a freshly-built jit program (trace + XLA
        compile happen synchronously inside that call) is recorded as a
        ``jit_compile`` span in the metrics stream; warm calls dispatch in
        microseconds and go unobserved.  Without the split, compile time
        silently pollutes the first epoch's throughput number — exactly
        the bias BASELINE round 5 tripped over.

        ISSUE 6: every call additionally feeds the recompilation sentinel
        — a NEW arg signature (shape/dtype tree) after the cold compile
        is a retrace, counted into ``jit.retraces`` (drift-gated) and
        recorded as a ``jit_compile`` span flagged ``retrace=True``; with
        ``profile.step_split`` the program also runs under the
        host-dispatch / device-execution timing split."""
        key = (kind, self._config_key())
        sentinel = self._sentinels.get(key)
        if sentinel is None:
            sentinel = self._sentinels[key] = obs_profile.RetraceSentinel(
                f"{type(self).__name__}.{kind}",
                registry=self._obs_registry, sink=self.metrics)
        step = obs_profile.step_split(run, registry=self._obs_registry) \
            if self.profile.step_split else run

        def wrapped(*args):
            state = sentinel.observe(args)
            if state == "warm":
                return step(*args)
            # compile calls bypass the step split: the seconds-long trace
            # + XLA compile would land as one step.host_seconds sample
            # and dominate a short profiling run — the jit_compile span
            # already accounts for compile time separately
            with self.tracer.span("jit_compile", kind=kind,
                                  trainer=type(self).__name__,
                                  **({"retrace": True}
                                     if state == "retrace" else {})):
                return run(*args)
        return wrapped

    def _profiled_run(self, run, epoch: int, *args):
        """One epoch-program call, optionally under a per-epoch
        ``jax.profiler`` capture (``profile.trace_dir`` /
        ``trace_epochs`` — ISSUE 6).  The capture blocks on the outputs
        before stopping so the trace holds THIS epoch's device work; the
        pipelined (uncaptured) epochs keep their no-sync dispatch."""
        if not self.profile.trace_epoch(epoch):
            return run(*args)
        with obs_profile.device_trace(
                os.path.join(self.profile.trace_dir, f"epoch{epoch}")):
            out = run(*args)
            jax.block_until_ready(out)
        return out

    def _window_run(self):
        """Cached jit window program — repeated ``train()`` calls on an
        unchanged trainer reuse the compiled executable instead of
        re-tracing (same shapes → no recompile)."""
        key = self._config_key()
        cached = getattr(self, "_run_cache", None)
        if cached is None or cached[0] != key:
            loss_fn, optimizer = self._resolve()
            run = make_window_fn(self.model, loss_fn, optimizer,
                                 compute_dtype=self.compute_dtype,
                                 remat=self.remat,
                                 aux_weight=self.aux_weight)
            self._run_cache = (key, run, optimizer)
        _, run, optimizer = self._run_cache
        return self._instrumented(run), optimizer

    def _finish(self, variables) -> Model:
        self.trained_variables = jax.tree_util.tree_map(_to_host, variables)
        self.model.variables = self.trained_variables
        return self.model

    def train(self, dataset: Dataset, shuffle: bool = False,
              resume: bool = False) -> Model:
        """Parity: reference ``Trainer.train(dataframe, shuffle)``.

        ``resume=True`` restarts from the latest checkpoint in
        ``checkpoint_dir`` (our addition — the reference has no mid-training
        persistence, SURVEY.md §5.4).
        """
        t0 = time.time()
        self._resume = bool(resume)
        try:
            with self.tracer.span("train", trainer=type(self).__name__,
                                  epochs=self.num_epoch):
                return self._train(dataset, shuffle)
        finally:
            self.training_time = time.time() - t0

    def _train(self, dataset: Dataset, shuffle: bool) -> Model:
        raise NotImplementedError

    # -- checkpoint plumbing -------------------------------------------------
    def _ckpt_manager(self) -> Optional[CheckpointManager]:
        if not self.checkpoint_dir:
            return None
        return CheckpointManager(self.checkpoint_dir, keep=self.checkpoint_keep)

    def _maybe_restore(self, ckpt, state):
        """Returns ``(state, start_epoch)``; restores iff resume requested."""
        if ckpt is None or not getattr(self, "_resume", False):
            return state, 0
        if ckpt.latest_step() is None:
            return state, 0
        state, meta = ckpt.restore(state)
        return state, int(meta.get("epoch", -1)) + 1

    def _epoch_metrics(self, epoch: int, losses: np.ndarray, dt: float,
                       samples: int) -> None:
        extra = {}
        if self.profile.memory:
            # memory watermark sample at the per-epoch heartbeat point
            # (ISSUE 6): mem.* gauges in the obs registry, live bytes on
            # the epoch record for obsview / --export-trace
            snap = obs_profile.observe_memory(self._obs_registry())
            extra["live_bytes"] = snap["live_bytes"]
        self.metrics.log("epoch", trainer=type(self).__name__, epoch=epoch,
                         mean_loss=float(np.mean(losses)),
                         epoch_seconds=dt,
                         samples_per_sec=samples / dt if dt > 0 else 0.0,
                         **extra)


class SingleTrainer(Trainer):
    """Single-worker baseline (reference ``SingleTrainer`` +
    ``SingleTrainerWorker``): the whole dataset on one chip, a jit-compiled
    ``lax.scan`` over minibatches per epoch.  The conformance anchor all
    distributed trainers are compared against.

    Also accepts a disk-backed ``data.streaming.ShardedFileDataset``:
    epochs then stream window-by-window from disk (``stream_window``
    batches per jit call) with bounded host memory — the ImageNet-scale
    input story (SURVEY.md §7 hard part 6)."""

    #: batches per jit window call on the streaming path (static shape;
    #: larger = fewer dispatches, more host RAM in flight)
    stream_window = 8

    def _train(self, dataset: Dataset, shuffle: bool) -> Model:
        from .data.streaming import ShardedFileDataset
        if isinstance(dataset, ShardedFileDataset):
            return self._train_stream(dataset, shuffle)
        if shuffle:
            dataset = dataset.shuffle(self.seed)
        run, optimizer = self._window_run()

        ds = dataset.coalesce(1)
        stacked, steps = ds.stacked([self.features_col, self.label_col],
                                    self.batch_size)
        xs = jnp.asarray(stacked[self.features_col][0])
        ys = jnp.asarray(stacked[self.label_col][0])

        variables = self.model.init(self.seed)
        opt_state = optimizer.init(variables["params"])
        rng = jax.random.PRNGKey(self.seed + 1)

        ckpt = self._ckpt_manager()
        (variables, opt_state, rng), start_epoch = self._maybe_restore(
            ckpt, (variables, opt_state, rng))
        samples = int(xs.shape[0]) * self.batch_size
        pipe = _EpochPipeline(self, samples)
        for epoch in range(start_epoch, self.num_epoch):
            variables, opt_state, rng, losses = self._profiled_run(
                run, epoch, variables, opt_state, rng, xs, ys)
            pipe.push(epoch, losses)
            if ckpt is not None:  # note: saving implies a per-epoch sync
                ckpt.save(epoch, (variables, opt_state, rng),
                          {"epoch": epoch})
        pipe.flush()
        return self._finish(variables)

    def _train_stream(self, source, shuffle: bool) -> Model:
        """Stream epochs from disk: the host assembles window w+1 (the
        prefetch thread / tf.data does the IO) while the device trains
        window w; loss readback is deferred to epoch edges as usual."""
        run, optimizer = self._window_run()
        bs = self.batch_size
        steps = source.steps_per_epoch(bs)
        if steps == 0:
            raise ValueError(f"batch_size {bs} exceeds dataset rows "
                             f"{source.num_rows}")
        w = max(1, min(int(self.stream_window), steps))
        n_windows = steps // w

        variables = self.model.init(self.seed)
        opt_state = optimizer.init(variables["params"])
        rng = jax.random.PRNGKey(self.seed + 1)
        ckpt = self._ckpt_manager()
        (variables, opt_state, rng), start_epoch = self._maybe_restore(
            ckpt, (variables, opt_state, rng))

        cols = [self.features_col, self.label_col]
        samples = n_windows * w * bs
        pipe = _EpochPipeline(self, samples)
        for epoch in range(start_epoch, self.num_epoch):
            seed = (self.seed + 1000 + epoch) if shuffle else None
            it = source.batches(cols, bs, seed=seed)
            epoch_losses = []
            try:
                for _ in range(n_windows):
                    window = [next(it) for _ in range(w)]
                    wx = np.stack([b[0] for b in window])
                    wy = np.stack([b[1] for b in window])
                    variables, opt_state, rng, losses = run(
                        variables, opt_state, rng, jnp.asarray(wx),
                        jnp.asarray(wy))
                    epoch_losses.append(losses)
            finally:
                # the epoch takes exactly n_windows*w batches; close the
                # stream so the prefetch thread releases its shard now
                if hasattr(it, "close"):
                    it.close()
            pipe.push(epoch, jnp.concatenate(epoch_losses))
            if ckpt is not None:
                ckpt.save(epoch, (variables, opt_state, rng),
                          {"epoch": epoch})
        pipe.flush()
        return self._finish(variables)


class DistributedTrainer(Trainer):
    """Base for multi-worker trainers (reference ``DistributedTrainer``):
    owns ``num_workers``, partitions the dataset one-partition-per-worker,
    and drives the epoch program.  Subclasses pick the communication rule
    (sync mode) / parameter-server flavor (async mode)."""

    #: default window when the algorithm has no explicit one
    _default_window = 1

    def __init__(self, keras_model: Model, worker_optimizer="sgd",
                 loss="categorical_crossentropy", num_workers: int = 2,
                 features_col: str = "features", label_col: str = "label",
                 num_epoch: int = 1, batch_size: int = 32,
                 communication_window: Optional[int] = None,
                 learning_rate: float = 0.01, seed: int = 0,
                 mode: str = "sync", mesh=None,
                 async_workers: str = "threads",
                 comm_codec: str = "none",
                 comm_down: str = "none",
                 ps_shm: bool = False,
                 pull_overlap: bool = False,
                 ps_shards: int = 1,
                 heartbeat_hard_s: float = 30.0,
                 startup_grace_s: float = 300.0, **kw):
        super().__init__(keras_model, worker_optimizer, loss, features_col,
                         label_col, num_epoch, batch_size, learning_rate, seed,
                         **kw)
        self.num_workers = int(num_workers)
        #: fleet self-healing knobs (ISSUE 9, async mode): a worker whose
        #: commits/pulls stop reaching the PS for ``heartbeat_hard_s`` is
        #: evicted and respawned by the live supervisor;
        #: ``startup_grace_s`` applies instead until an incarnation's
        #: first commit (interpreter start + jit compile must not read as
        #: a stall)
        self.heartbeat_hard_s = float(heartbeat_hard_s)
        self.startup_grace_s = float(startup_grace_s)
        #: live fleet supervisor, set only while an async run is in
        #: flight — the ``add_worker`` elastic-join seam
        self._supervisor = None
        self.communication_window = int(
            communication_window if communication_window is not None
            else self._default_window)
        if mode not in ("sync", "async"):
            raise ValueError(f"mode must be 'sync' or 'async', got {mode!r}")
        if async_workers not in ("threads", "processes"):
            raise ValueError(f"async_workers must be 'threads' or "
                             f"'processes', got {async_workers!r}")
        self.mode = mode
        self.mesh = mesh
        #: async-mode worker placement: in-process threads (fast, hermetic)
        #: or one OS process per worker — the reference's deployment shape
        #: (Spark executor tasks); see ``ps.runner`` / ``ps.worker_main``.
        self.async_workers = async_workers
        #: async-mode center sharding (ISSUE 10): 1 (default) hosts the
        #: center on one SocketParameterServer — bit-identical to the
        #: pre-shard behavior; N > 1 partitions the center pytree across
        #: N shard servers (``ps.shard``), each with its own lock/accept
        #: loop/pull cache, and workers fan commits/pulls out in parallel
        #: with consistent-cut assembly.
        self.ps_shards = int(ps_shards)
        if self.ps_shards < 1:
            raise ValueError(f"ps_shards must be >= 1, got {ps_shards}")
        #: async-mode commit compression (``ps.codecs``): "none" (default,
        #: bit-identical numerics), "int8", "bf16", or "topk<frac>" —
        #: quantized deltas with worker-side error feedback (ISSUE 4).
        #: Sync mode communicates on-device (ICI collectives); no codec.
        from .ps.codecs import Codec, get_codec, validate_down_spec
        if isinstance(comm_codec, Codec):
            # a Codec INSTANCE carries per-worker mutable error-feedback
            # state and cannot be shared by N workers (racing residuals);
            # keep only its spec — every worker builds its own instance
            comm_codec = comm_codec.name
        get_codec(comm_codec)  # validate the spec at construction time
        self.comm_codec = comm_codec
        #: async-mode DOWN pull compression (ISSUE 12): "none" (default —
        #: raw pulls, bit-identical wire), "int8"/"bf16"/"topk<frac>"
        #: (quantized residuals against the server's shared reference
        #: center), or "adaptive" (per-link codec chosen from measured
        #: pull RTTs, with hysteresis and a recorded switch trail)
        self.comm_down = validate_down_spec(comm_down)
        #: async-mode same-host shared-memory transport (ISSUE 12): offer
        #: shm rings in the hello on every PS connection — co-located
        #: peers (thread-placed fleets; the cluster runner's process-0
        #: host) skip the kernel socket path, cross-host peers are
        #: refused at the capability probe and stay on TCP untouched
        self.ps_shm = bool(ps_shm)
        #: async-mode dispatch-ahead pulls (ISSUE 15): each pull-first
        #: worker issues window k+1's pull right after window k's device
        #: step is dispatched, hiding the center transfer behind compute
        #: (``ps.pull.hidden_seconds`` / ``ps.pull.overlap_fraction``)
        #: at the cost of one window of self-staleness — the regime the
        #: async update rules already absorb.  Streamed pull replies
        #: themselves (the ``DKW4`` chunk wire) are negotiated per
        #: connection and on by default; ``DKTPU_STREAM=0`` opts out.
        self.pull_overlap = bool(pull_overlap)

    # -- fleet elasticity (ISSUE 9) -----------------------------------------
    def add_worker(self, worker_id=None) -> int:
        """Elastic join: add a worker to the LIVE async run (``train()``
        currently blocking on another thread).  The new worker pulls the
        current center and starts committing, fully accounted by the PS
        (``ps.joins``).  With no id, the next unused one is picked.
        Returns the worker id."""
        sup = self._supervisor
        if sup is None:
            raise RuntimeError(
                "no live async run to join — add_worker() is valid only "
                "while train(mode='async') is in flight")
        return sup.add_worker(worker_id)

    # -- algorithm hooks ----------------------------------------------------
    def _sync_algorithm(self):
        raise NotImplementedError

    def _ps_factory(self):
        """Async-mode parameter-server factory; see ``distkeras_tpu.ps``."""
        raise NotImplementedError(
            f"{type(self).__name__} has no async parameter-server mode")

    # -- data staging -------------------------------------------------------
    def _stage_data(self, dataset: Dataset, window: int):
        """(P, n_windows, window, batch, ...) device arrays, sharded on the
        workers axis — Spark's repartition+ship collapsed to one transfer."""
        ds = dataset.repartition(self.num_workers)
        stacked, steps = ds.stacked([self.features_col, self.label_col],
                                    self.batch_size)
        n_windows = steps // window
        if n_windows == 0:
            raise ValueError(
                f"communication_window {window} exceeds the {steps} "
                f"steps available per worker (decrease window/batch_size "
                f"or add data)")
        dropped = steps - n_windows * window
        if dropped:
            warnings.warn(
                f"{dropped} of {steps} per-worker batches don't fill a "
                f"communication_window of {window} and are dropped each "
                f"epoch (static shapes require whole windows); pick a "
                f"window dividing {steps} to use all data", stacklevel=3)

        def shape_windows(a):
            a = a[:, : n_windows * window]
            return a.reshape(a.shape[0], n_windows, window, *a.shape[2:])

        xs = shape_windows(stacked[self.features_col])
        ys = shape_windows(stacked[self.label_col])
        return xs, ys, n_windows

    # -- training -----------------------------------------------------------
    def _train(self, dataset: Dataset, shuffle: bool) -> Model:
        from .data.streaming import ShardedFileDataset
        if isinstance(dataset, ShardedFileDataset):
            # disk-streaming path: every worker streams ITS shard partition
            # (partition == worker, SURVEY.md §3.1 boundary #1); the whole
            # epoch is never resident in host RAM or HBM
            if self.mode == "async":
                return self._train_async(dataset, stream_shuffle=shuffle)
            return self._train_sync_stream(dataset, shuffle)
        if shuffle:
            dataset = dataset.shuffle(self.seed)
        if self.mode == "async":
            return self._train_async(dataset)
        return self._train_sync(dataset)

    def _config_key(self) -> tuple:
        return super()._config_key() + (
            self.num_workers, self.communication_window,
            id(self.mesh) if self.mesh is not None else None,
            getattr(self, "rho", None), getattr(self, "momentum", None))

    def _engine_parts(self):
        """Cached (engine, mesh, optimizer, programs) for the current
        hyperparameters; ``programs`` caches the compiled epoch/window
        executables so repeated ``train()`` calls skip re-tracing."""
        key = self._config_key()
        cached = getattr(self, "_engine_cache", None)
        if cached is None or cached[0] != key:
            loss_fn, optimizer = self._resolve()
            mesh = self.mesh if self.mesh is not None else mesh_lib.make_mesh(
                self.num_workers)
            engine = SyncEngine(self.model, loss_fn, optimizer,
                                self._sync_algorithm(), self.num_workers,
                                self.communication_window, mesh=mesh,
                                compute_dtype=self.compute_dtype,
                                remat=self.remat,
                                aux_weight=self.aux_weight)
            self._engine_cache = (key, engine, mesh, optimizer, {})
        return self._engine_cache[1:]

    def _engine_run(self):
        """Cached jit epoch program + mesh + optimizer (see
        ``Trainer._window_run`` — same reuse-across-train()-calls story)."""
        engine, mesh, optimizer, programs = self._engine_parts()
        if "epoch" not in programs:
            programs["epoch"] = engine.epoch_fn()
        return self._instrumented(programs["epoch"], "epoch"), mesh, optimizer

    def _engine_window(self):
        """Cached jit single-window program (streaming path)."""
        engine, mesh, optimizer, programs = self._engine_parts()
        if "window" not in programs:
            programs["window"] = engine.window_fn()
        return (self._instrumented(programs["window"], "window"), mesh,
                optimizer)

    def _train_sync(self, dataset: Dataset) -> Model:
        run, mesh, optimizer = self._engine_run()
        P = self.num_workers

        xs, ys, _ = self._stage_data(dataset, self.communication_window)
        xs = mesh_lib.host_to_mesh(mesh, xs)
        ys = mesh_lib.host_to_mesh(mesh, ys)

        center = self.model.init(self.seed)
        center = mesh_lib.broadcast_to_mesh(mesh, center)
        local = tmap(lambda x: np.broadcast_to(np.asarray(x)[None],
                                               (P, *np.shape(x))), center)
        local = mesh_lib.host_to_mesh(mesh, local)
        opt_state = jax.vmap(optimizer.init)(local["params"])
        rngs = jax.random.split(jax.random.PRNGKey(self.seed + 1), P)
        rngs = mesh_lib.host_to_mesh(mesh, rngs)

        ckpt = self._ckpt_manager()
        (center, local, opt_state, rngs), start_epoch = self._maybe_restore(
            ckpt, (center, local, opt_state, rngs))
        if start_epoch:  # restored host arrays need re-placing on the mesh
            center = mesh_lib.broadcast_to_mesh(mesh, center)
            local = mesh_lib.host_to_mesh(mesh, local)
            opt_state = mesh_lib.host_to_mesh(mesh, opt_state)
            rngs = mesh_lib.host_to_mesh(mesh, rngs)
        samples = int(xs.shape[1]) * int(xs.shape[2]) * self.batch_size * P
        pipe = _EpochPipeline(self, samples, reshape=(P, -1))
        for epoch in range(start_epoch, self.num_epoch):
            center, local, opt_state, rngs, losses = self._profiled_run(
                run, epoch, center, local, opt_state, rngs, xs, ys)
            pipe.push(epoch, losses)  # history rows: (workers, steps)
            if ckpt is not None:  # note: saving implies a per-epoch sync
                ckpt.save(epoch, (center, local, opt_state, rngs),
                          {"epoch": epoch})
        pipe.flush()
        return self._collect(center, local)

    def _collect(self, center, local) -> Model:
        """Final model = the center variable (reference: trainers return
        ``PS.get_model()``)."""
        return self._finish(center)

    # -- disk-streaming sync path (SURVEY.md §7 hard part 6) ----------------
    def _stream_locals(self, P: int):
        """(center, local) initial host pytrees for the streaming path;
        local's leading axis is workers.  Default: all workers start from
        the center init (EnsembleTrainer decorrelates seeds instead)."""
        center = self.model.init(self.seed)
        local = tmap(lambda x: np.broadcast_to(np.asarray(x)[None],
                                               (P, *np.shape(x))), center)
        return center, local

    def _train_sync_stream(self, source, shuffle: bool) -> Model:
        """Synchronous epochs streamed from disk: each worker's shard
        partition feeds its mesh slot window-by-window; the host (with
        per-worker prefetch threads) assembles window w+1 while the devices
        train window w.  Peak host memory is O(P × window × batch), never
        the epoch."""
        from .data.streaming import (worker_window_factory,
                                     worker_windows_per_epoch)
        run, mesh, optimizer = self._engine_window()
        P = self.num_workers
        w = self.communication_window
        bs = self.batch_size
        n_windows = worker_windows_per_epoch(source, bs, P, w)

        center, local = self._stream_locals(P)
        center = mesh_lib.broadcast_to_mesh(mesh, center)
        local = mesh_lib.host_to_mesh(mesh, local)
        opt_state = jax.vmap(optimizer.init)(local["params"])
        rngs = jax.random.split(jax.random.PRNGKey(self.seed + 1), P)
        rngs = mesh_lib.host_to_mesh(mesh, rngs)

        ckpt = self._ckpt_manager()
        (center, local, opt_state, rngs), start_epoch = self._maybe_restore(
            ckpt, (center, local, opt_state, rngs))
        if start_epoch:  # restored host arrays need re-placing on the mesh
            center = mesh_lib.broadcast_to_mesh(mesh, center)
            local = mesh_lib.host_to_mesh(mesh, local)
            opt_state = mesh_lib.host_to_mesh(mesh, opt_state)
            rngs = mesh_lib.host_to_mesh(mesh, rngs)

        cols = [self.features_col, self.label_col]
        factories = [worker_window_factory(source, cols, bs, k, P, w,
                                           self.seed, shuffle)
                     for k in range(P)]
        samples = n_windows * w * bs * P
        pipe = _EpochPipeline(self, samples, reshape=(P, -1))
        for epoch in range(start_epoch, self.num_epoch):
            its = [f(epoch) for f in factories]
            losses = []
            try:
                for _ in range(n_windows):
                    grp = [next(it) for it in its]
                    wx = np.stack([g[0] for g in grp])  # (P, w, B, ...)
                    wy = np.stack([g[1] for g in grp])
                    center, local, opt_state, rngs, l = run(
                        center, local, opt_state, rngs,
                        mesh_lib.host_to_mesh(mesh, wx),
                        mesh_lib.host_to_mesh(mesh, wy))
                    losses.append(l)  # (P, w) device array, not synced
            finally:
                for it in its:
                    it.close()
            pipe.push(epoch, jnp.concatenate(losses, axis=1))
            if ckpt is not None:  # note: saving implies a per-epoch sync
                ckpt.save(epoch, (center, local, opt_state, rngs),
                          {"epoch": epoch})
        pipe.flush()
        return self._collect(center, local)

    def _train_async(self, dataset, stream_shuffle: Optional[bool] = None):
        try:
            from .ps.runner import run_async_training
        except ImportError as e:
            raise NotImplementedError(
                "async parameter-server mode requires the distkeras_tpu.ps "
                "package") from e
        return run_async_training(self, dataset,
                                  stream_shuffle=stream_shuffle)


class AveragingTrainer(DistributedTrainer):
    """Model averaging (reference ``AveragingTrainer``): workers train
    completely independently on their partition; the final model is the
    plain average of all worker models."""

    def __init__(self, keras_model, worker_optimizer="sgd",
                 loss="categorical_crossentropy", num_workers: int = 2,
                 **kw):
        super().__init__(keras_model, worker_optimizer, loss, num_workers, **kw)

    def _sync_algorithm(self):
        return NoCommSync()

    def _collect(self, center, local) -> Model:
        averaged = tmap(lambda l: jnp.mean(l, axis=0), local)
        return self._finish(averaged)


class EnsembleTrainer(DistributedTrainer):
    """Ensemble training (reference ``EnsembleTrainer``): N independent
    models (different partitions AND different init seeds), all returned.
    ``train`` returns a list of Models."""

    def __init__(self, keras_model, worker_optimizer="sgd",
                 loss="categorical_crossentropy", num_ensembles: int = 2,
                 **kw):
        super().__init__(keras_model, worker_optimizer, loss,
                         num_workers=num_ensembles, **kw)
        self.num_ensembles = int(num_ensembles)

    def _sync_algorithm(self):
        return NoCommSync()

    def _stream_locals(self, P: int):
        # independent decorrelated inits per ensemble member (same rule as
        # the in-RAM path below)
        fresh = getattr(self.model, "reinit", self.model.init)
        inits = [fresh(self.seed + i) for i in range(P)]
        local = tmap(lambda *xs_: np.stack([np.asarray(x) for x in xs_]),
                     *inits)
        return inits[0], local

    def _collect(self, center, local):
        # N independent models, all returned (in-RAM and streaming paths;
        # on a multi-process mesh the worker-sharded stack allgathers)
        local = jax.tree_util.tree_map(_to_host, local)
        models = []
        for i in range(self.num_workers):
            # type(...) so ingested Keras models (KerasAdapter) work too
            m = type(self.model).from_config(self.model.config())
            m.variables = tmap(lambda l: l[i], local)
            models.append(m)
        self.trained_variables = models[0].variables
        return models

    def _train_sync(self, dataset: Dataset):
        run, mesh, optimizer = self._engine_run()
        P = self.num_workers

        xs, ys, _ = self._stage_data(dataset, self.communication_window)
        xs = mesh_lib.host_to_mesh(mesh, xs)
        ys = mesh_lib.host_to_mesh(mesh, ys)

        # independent inits per ensemble member (reinit = deliberate fresh
        # decorrelated init; Keras adapters keep init() as the pretrained
        # snapshot and expose reinit separately)
        fresh = getattr(self.model, "reinit", self.model.init)
        inits = [fresh(self.seed + i) for i in range(P)]
        local = tmap(lambda *xs_: np.stack([np.asarray(x) for x in xs_]),
                     *inits)
        local = mesh_lib.host_to_mesh(mesh, local)
        center = mesh_lib.broadcast_to_mesh(mesh, inits[0])
        opt_state = jax.vmap(optimizer.init)(local["params"])
        rngs = jax.random.split(jax.random.PRNGKey(self.seed + 1), P)
        rngs = mesh_lib.host_to_mesh(mesh, rngs)

        ckpt = self._ckpt_manager()
        (center, local, opt_state, rngs), start_epoch = self._maybe_restore(
            ckpt, (center, local, opt_state, rngs))
        if start_epoch:  # restored host arrays need re-placing on the mesh
            center = mesh_lib.broadcast_to_mesh(mesh, center)
            local = mesh_lib.host_to_mesh(mesh, local)
            opt_state = mesh_lib.host_to_mesh(mesh, opt_state)
            rngs = mesh_lib.host_to_mesh(mesh, rngs)
        samples = int(xs.shape[1]) * int(xs.shape[2]) * self.batch_size * P
        pipe = _EpochPipeline(self, samples, reshape=(P, -1))
        for epoch in range(start_epoch, self.num_epoch):
            center, local, opt_state, rngs, losses = self._profiled_run(
                run, epoch, center, local, opt_state, rngs, xs, ys)
            pipe.push(epoch, losses)
            if ckpt is not None:
                ckpt.save(epoch, (center, local, opt_state, rngs),
                          {"epoch": epoch})
        pipe.flush()
        return self._collect(center, local)


class SpmdTrainer(Trainer):
    """Multi-axis GSPMD trainer — the TPU-native strategy beyond the
    reference's data parallelism: one jit-compiled train step over a
    dp × mp mesh; XLA inserts the gradient all-reduce (dp) and partitions
    large matmuls (mp) from sharding annotations alone
    (``parallel.spmd``).  No reference equivalent; this is where models
    too large to replicate train.

    ``mesh_shape``: e.g. ``{"dp": 2, "mp": 4}`` (defaults to all devices
    on dp).  Also accepts a disk-backed ``ShardedFileDataset``: epochs
    then stream window-by-window with dp-sharded batches and mp-sharded
    params (``_train_stream``).
    """

    def __init__(self, keras_model: Model, worker_optimizer="sgd",
                 loss="categorical_crossentropy",
                 mesh_shape: Optional[dict] = None, **kw):
        super().__init__(keras_model, worker_optimizer, loss, **kw)
        self.mesh_shape = mesh_shape
        #: filled per ``train()``: per-leaf PartitionSpec + global vs
        #: per-device bytes (``spmd.sharding_report``) — the audit that mp
        #: actually sharded parameters (VERDICT r3 weak #3)
        self.sharding_report: Optional[dict] = None
        #: the AOT-compiled window executable; ``.as_text()`` is the HLO
        #: tests grep for the expected collectives
        self.compiled_step = None

    def _config_key(self) -> tuple:
        # the mesh (and thus the compiled program + AOT executable) is
        # cached under this key — mesh_shape edits must invalidate it
        return super()._config_key() + (
            tuple(sorted(self.mesh_shape.items())) if self.mesh_shape
            else None,)

    def _window_run(self):
        """Like ``Trainer._window_run`` but the forward is wrapped in
        activation sharding anchors (``spmd.constrained_model``) so the
        intended dp/mp sharding is part of the traced program, not just a
        placement hint."""
        from .parallel import spmd
        key = self._config_key()
        cached = getattr(self, "_run_cache", None)
        if cached is None or cached[0] != key:
            loss_fn, optimizer = self._resolve()
            if self.mesh_shape:
                axes, sizes = zip(*self.mesh_shape.items())
            else:
                axes, sizes = ("dp",), (len(jax.devices()),)
            mesh = mesh_lib.make_mesh(axis_names=axes, shape=sizes)
            dp = "dp" if "dp" in axes else axes[0]
            proxy = spmd.constrained_model(self.model, mesh, dp)
            run = make_window_fn(proxy, loss_fn, optimizer,
                                 compute_dtype=self.compute_dtype,
                                 remat=self.remat,
                                 aux_weight=self.aux_weight)
            self._run_cache = (key, run, optimizer, mesh, dp)
        return self._run_cache[1:]

    def _train_stream(self, source, shuffle: bool) -> Model:
        """Disk-streaming GSPMD epochs: windows assemble on the host while
        the mesh trains the previous one; batches land batch-sharded over
        dp, params stay mp-sharded — ImageNet-scale inputs for models too
        large to replicate (SURVEY.md §7 hard part 6 × GSPMD)."""
        from .data.streaming import window_batches
        from .parallel import spmd
        run, optimizer, mesh, dp = self._window_run()
        run = self._instrumented(run)
        bs = self.batch_size
        steps = source.steps_per_epoch(bs)
        if steps == 0:
            raise ValueError(f"batch_size {bs} exceeds dataset rows "
                             f"{source.num_rows}")
        w = max(1, min(int(SingleTrainer.stream_window), steps))
        n_windows = steps // w

        variables = self.model.init(self.seed)
        specs = spmd.infer_param_specs(variables["params"], mesh)
        variables = {"params": spmd.place(variables["params"], mesh, specs),
                     "state": spmd.replicate(variables["state"], mesh)}
        self.sharding_report = spmd.sharding_report(variables["params"])
        opt_state = optimizer.init(variables["params"])
        rng = spmd.put(jax.random.PRNGKey(self.seed + 1),
                       jax.sharding.NamedSharding(
                           mesh, jax.sharding.PartitionSpec()))
        ckpt = self._ckpt_manager()
        opt_shardings = jax.tree_util.tree_map(lambda x: x.sharding,
                                               opt_state)
        (variables, opt_state, rng), start_epoch = self._maybe_restore(
            ckpt, (variables, opt_state, rng))
        if start_epoch:  # restored host arrays: re-apply GSPMD placement
            variables = {
                "params": spmd.place(variables["params"], mesh, specs),
                "state": spmd.replicate(variables["state"], mesh)}
            opt_state = jax.tree_util.tree_map(
                spmd.put, opt_state, opt_shardings)
            rng = spmd.put(rng, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()))

        bsh = spmd.batch_sharding(mesh, dp, batch_dim=1)  # (w, batch, ...)
        cols = [self.features_col, self.label_col]
        samples = n_windows * w * bs
        pipe = _EpochPipeline(self, samples)
        for epoch in range(start_epoch, self.num_epoch):
            seed = (self.seed + 1000 + epoch) if shuffle else None
            it = window_batches(source.batches(cols, bs, seed=seed), w)
            losses = []
            try:
                for _ in range(n_windows):
                    wx, wy = next(it)
                    variables, opt_state, rng, l = run(
                        variables, opt_state, rng,
                        spmd.put(wx, bsh), spmd.put(wy, bsh))
                    losses.append(l)
            finally:
                it.close()
            pipe.push(epoch, jnp.concatenate(losses))
            if ckpt is not None:
                ckpt.save(epoch, (variables, opt_state, rng),
                          {"epoch": epoch})
        pipe.flush()
        return self._finish(variables)

    def _train(self, dataset: Dataset, shuffle: bool) -> Model:
        from .data.streaming import ShardedFileDataset
        from .parallel import spmd
        if isinstance(dataset, ShardedFileDataset):
            return self._train_stream(dataset, shuffle)
        if shuffle:
            dataset = dataset.shuffle(self.seed)
        run, optimizer, mesh, dp = self._window_run()

        ds = dataset.coalesce(1)
        stacked, steps = ds.stacked([self.features_col, self.label_col],
                                    self.batch_size)
        bsh = spmd.batch_sharding(mesh, dp, batch_dim=1)  # (steps, batch,...)
        xs = spmd.put(stacked[self.features_col][0], bsh)
        ys = spmd.put(stacked[self.label_col][0], bsh)

        variables = self.model.init(self.seed)
        specs = spmd.infer_param_specs(variables["params"], mesh)
        variables = {"params": spmd.place(variables["params"], mesh, specs),
                     "state": spmd.replicate(variables["state"], mesh)}
        self.sharding_report = spmd.sharding_report(variables["params"])
        opt_state = optimizer.init(variables["params"])
        rng = spmd.put(jax.random.PRNGKey(self.seed + 1),
                       jax.sharding.NamedSharding(
                           mesh, jax.sharding.PartitionSpec()))

        ckpt = self._ckpt_manager()
        # shardings of the freshly-initialized state, to re-apply on resume
        opt_shardings = jax.tree_util.tree_map(lambda x: x.sharding, opt_state)
        (variables, opt_state, rng), start_epoch = self._maybe_restore(
            ckpt, (variables, opt_state, rng))
        if start_epoch:  # restored host arrays: re-apply GSPMD placement
            variables = {
                "params": spmd.place(variables["params"], mesh, specs),
                "state": spmd.replicate(variables["state"], mesh)}
            opt_state = jax.tree_util.tree_map(
                spmd.put, opt_state, opt_shardings)
            rng = spmd.put(rng, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()))
        # AOT-compile the window program (replaces the implicit jit-cache
        # call): one compile per (config, shapes), and the executable stays
        # inspectable — tests grep compiled_step.as_text() for the
        # dp all-reduce / mp collectives (VERDICT r3 weak #3).  Carry-out
        # shardings are pinned to carry-in so epoch N+1's inputs (epoch
        # N's outputs) always match the executable — XLA would otherwise
        # be free to re-shard outputs (e.g. a bias to P('mp')) and the
        # strict AOT call would reject them on the next epoch.
        akey = (self._config_key(), xs.shape, str(xs.dtype),
                ys.shape, str(ys.dtype))
        cached = getattr(self, "_aot_cache", None)
        if cached is None or cached[0] != akey:
            carry_sh = jax.tree_util.tree_map(
                lambda a: a.sharding, (variables, opt_state, rng))
            out_sh = (*carry_sh, mesh_lib.replicated(mesh))  # losses
            pinned = jax.jit(run, donate_argnums=(0, 1, 2),
                             out_shardings=out_sh)
            # retrace sentinel for the AOT seam (ISSUE 6): the explicit
            # compile is the entry point here, so feed the sentinel the
            # data shapes directly — a second compile under the same
            # config is a shape-change retrace, counted like the
            # implicit-jit paths
            sentinel = self._sentinels.get(("aot", self._config_key()))
            if sentinel is None:
                sentinel = self._sentinels[("aot", self._config_key())] = \
                    obs_profile.RetraceSentinel(
                        f"{type(self).__name__}.aot",
                        registry=self._obs_registry, sink=self.metrics)
            state = sentinel.observe((xs, ys))
            # explicit AOT compile: the one place compile time is exactly
            # measurable rather than inferred from a cold first step
            with self.tracer.span("aot_compile",
                                  trainer=type(self).__name__,
                                  **({"retrace": True}
                                     if state == "retrace" else {})):
                self._aot_cache = (akey,
                                   pinned.lower(variables, opt_state, rng,
                                                xs, ys).compile())
        compiled = self.compiled_step = self._aot_cache[1]
        samples = int(xs.shape[0]) * self.batch_size
        pipe = _EpochPipeline(self, samples)
        for epoch in range(start_epoch, self.num_epoch):
            variables, opt_state, rng, losses = self._profiled_run(
                compiled, epoch, variables, opt_state, rng, xs, ys)
            pipe.push(epoch, losses)
            if ckpt is not None:  # note: saving implies a per-epoch sync
                ckpt.save(epoch, (variables, opt_state, rng), {"epoch": epoch})
        pipe.flush()
        return self._finish(variables)


class _PipelinedSequential:
    """Forward proxy splitting a Sequential into pre → S pipeline stages →
    post, with the stage segment running GPipe over the ``pp`` mesh axis
    (``parallel.pipeline.pipeline_apply_sharded``).  Quacks enough like a
    Model for ``make_local_step`` (``.layer.apply``); params/state arrive
    regrouped as ``{"pre": [...], "stages": <stacked>, "post": [...]}``.

    Stages run ``train=False`` and rng-free inside the schedule (the
    GPipe scan cannot thread per-layer rng; transformer blocks —
    LayerNorm/attention/Dense — behave identically either way, and
    ``PipelineTrainer`` refuses stage segments with mutable state)."""

    def __init__(self, pre, stage_layers, post, mesh, num_microbatches,
                 stage_state_template, axis="pp", dp_axis=None):
        self.pre = pre
        self.stage_layers = stage_layers
        self.post = post
        self.pp_mesh = mesh
        self.num_microbatches = int(num_microbatches)
        #: per-stage-layer state trees (leafless — enforced by the
        #: trainer) with the layers' expected nesting (e.g. Residual's
        #: {"inner": {}}), threaded through stage applies unchanged
        self.stage_state_template = stage_state_template
        self.axis = axis
        self.dp_axis = dp_axis
        self.layer = self  # make_local_step calls model.layer.apply

    def _run(self, layers, params, state, x, train, rng):
        new_state = []
        for i, lyr in enumerate(layers):
            sub = None
            if rng is not None:
                rng, sub = jax.random.split(rng)
            x, s = lyr.apply(params[i], state[i], x, train=train, rng=sub)
            new_state.append(s)
        return x, new_state

    def apply(self, params, state, x, *, train=False, rng=None):
        from .parallel.pipeline import pipeline_apply_sharded
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        h, pre_state = self._run(self.pre, params["pre"], state["pre"], x,
                                 train, r1)
        tmpl = self.stage_state_template

        def stage_fn(sp, t):
            for j, lyr in enumerate(self.stage_layers):
                t, _ = lyr.apply(sp[j], tmpl[j], t, train=False, rng=None)
            return t

        h = pipeline_apply_sharded(
            self.pp_mesh, stage_fn, params["stages"], h,
            num_microbatches=self.num_microbatches, axis=self.axis,
            dp_axis=self.dp_axis)
        y, post_state = self._run(self.post, params["post"], state["post"],
                                  h, train, r2)
        return y, {"pre": pre_state, "stages": state["stages"],
                   "post": post_state}


class PipelineTrainer(Trainer):
    """Pipeline-parallel trainer (GPipe) — pp as a first-class trainer
    strategy, like mp on ``SpmdTrainer`` (VERDICT r3 missing #2; no
    reference equivalent — SURVEY.md §2 lists data parallelism as the
    reference's only strategy).

    The model's homogeneous block segment (auto-detected:
    ``parallel.pipeline.find_stage_segment``; e.g. ``zoo.gpt_lm``'s
    repeated transformer blocks) is laid out one-group-per-device along
    the ``pp`` mesh axis; embedding/head layers before/after the segment
    run replicated.  M microbatches flow through the schedule inside ONE
    jit train step, composing with dp via ``mesh_shape={"pp": S,
    "dp": D}`` (each dp replica pipelines its batch slice; XLA inserts
    the grad all-reduce).

    Gradient math is EXACT vs sequential training (GPipe reorders
    microbatch compute, it does not approximate), so the loss trajectory
    matches ``SingleTrainer`` on the same data/seed.
    """

    def __init__(self, keras_model: Model, worker_optimizer="sgd",
                 loss="categorical_crossentropy",
                 mesh_shape: Optional[dict] = None,
                 num_microbatches: Optional[int] = None, **kw):
        super().__init__(keras_model, worker_optimizer, loss, **kw)
        self.mesh_shape = mesh_shape or {"pp": len(jax.devices())}
        if "pp" not in self.mesh_shape:
            raise ValueError(f"mesh_shape needs a 'pp' axis, got "
                             f"{self.mesh_shape}")
        self.num_microbatches = num_microbatches

    def _split_model(self, mesh):
        """Regroup the Sequential's variables into pre/stages/post and
        build the pipelined forward proxy."""
        from .parallel.pipeline import find_stage_segment, stack_stage_params
        layer = self.model.layer
        if not isinstance(layer, Sequential):
            raise ValueError("PipelineTrainer needs a Sequential model "
                             f"(got {type(layer).__name__})")
        S = mesh.shape["pp"]
        a, g = find_stage_segment(layer.layers, S,
                                  input_shape=self.model.input_shape)
        variables = self.model.init(self.seed)
        params, state = variables["params"], variables["state"]
        span = S * g
        stage_state = state[a:a + span]
        if jax.tree_util.tree_leaves(stage_state):
            raise ValueError(
                "pipeline stages must be stateless (the GPipe scan cannot "
                "thread per-stage mutable state); the detected segment "
                f"[{a}:{a + span}] carries state — train this model with "
                "SpmdTrainer or the dp trainers instead")
        rng_layers = [type(sub).__name__
                      for lyr in layer.layers[a:a + g]
                      for sub in lyr.iter_layers() if sub.rng_in_train]
        if rng_layers:
            raise ValueError(
                f"pipeline stages contain rng-consuming layers "
                f"{rng_layers} (Dropout): the GPipe schedule cannot thread "
                f"per-layer rng, and running them eval-mode would silently "
                f"train different math than SingleTrainer — remove them "
                f"from the repeated blocks or train with SpmdTrainer")
        stacked = stack_stage_params(
            [params[a + i * g:a + (i + 1) * g] for i in range(S)])
        grouped = {
            "params": {"pre": params[:a], "stages": stacked,
                       "post": params[a + span:]},
            "state": {"pre": state[:a], "stages": [],
                      "post": state[a + span:]},
        }
        #: leafless per-layer state structure of one stage group, for the
        #: stage applies and for rebuilding the flat variables at collect
        self._stage_state_template = stage_state[:g]
        self._stage_state_full = stage_state
        M = self.num_microbatches or S
        dp_axis = "dp" if "dp" in self.mesh_shape else None
        proxy = _PipelinedSequential(layer.layers[:a], layer.layers[a:a + g],
                                     layer.layers[a + span:], mesh, M,
                                     self._stage_state_template,
                                     dp_axis=dp_axis)
        return proxy, grouped, (a, g, S)

    def _config_key(self) -> tuple:
        return super()._config_key() + (
            tuple(sorted(self.mesh_shape.items())), self.num_microbatches)

    def _train(self, dataset: Dataset, shuffle: bool) -> Model:
        from .parallel import spmd
        if shuffle:
            dataset = dataset.shuffle(self.seed)

        axes, sizes = zip(*self.mesh_shape.items())
        mesh = mesh_lib.make_mesh(axis_names=axes, shape=sizes)
        proxy, variables, (a, g, S) = self._split_model(mesh)

        key = self._config_key()
        cached = getattr(self, "_run_cache", None)
        if cached is None or cached[0] != key:
            loss_fn, optimizer = self._resolve()
            run = make_window_fn(proxy, loss_fn, optimizer,
                                 compute_dtype=self.compute_dtype,
                                 remat=self.remat,
                                 aux_weight=self.aux_weight)
            self._run_cache = (key, run, optimizer)
        run, optimizer = self._run_cache[1:]
        run = self._instrumented(run)

        ds = dataset.coalesce(1)
        stacked_data, steps = ds.stacked([self.features_col, self.label_col],
                                         self.batch_size)
        if "dp" in self.mesh_shape:
            bsh = spmd.batch_sharding(mesh, "dp", batch_dim=1)
        else:
            bsh = jax.sharding.NamedSharding(mesh,
                                             jax.sharding.PartitionSpec())
        xs = spmd.put(stacked_data[self.features_col][0], bsh)
        ys = spmd.put(stacked_data[self.label_col][0], bsh)

        # placement: stage stacks sharded one-stage-per-device over pp;
        # pre/post replicated
        pp_sh = jax.sharding.NamedSharding(mesh,
                                           jax.sharding.PartitionSpec("pp"))
        rep = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        place = jax.tree_util.tree_map
        variables = {
            "params": {"pre": place(lambda x: spmd.put(x, rep),
                                    variables["params"]["pre"]),
                       "stages": place(lambda x: spmd.put(x, pp_sh),
                                       variables["params"]["stages"]),
                       "post": place(lambda x: spmd.put(x, rep),
                                     variables["params"]["post"])},
            "state": variables["state"],
        }
        opt_state = optimizer.init(variables["params"])
        rng = spmd.put(jax.random.PRNGKey(self.seed + 1), rep)

        ckpt = self._ckpt_manager()
        # shardings of the fresh opt state (stage subtrees inherit the pp
        # placement from the params), to re-apply exactly on resume — a
        # replicated re-placement would blow per-device memory S× and
        # force a second resharding compile
        opt_shardings = place(lambda x: x.sharding, opt_state)
        (variables, opt_state, rng), start_epoch = self._maybe_restore(
            ckpt, (variables, opt_state, rng))
        if start_epoch:  # restored host arrays: re-apply placement
            variables = {
                "params": {"pre": place(lambda x: spmd.put(x, rep),
                                        variables["params"]["pre"]),
                           "stages": place(
                               lambda x: spmd.put(x, pp_sh),
                               variables["params"]["stages"]),
                           "post": place(lambda x: spmd.put(x, rep),
                                         variables["params"]["post"])},
                "state": variables["state"],
            }
            # mesh-spanning shardings (stage moments inherit P('pp') via
            # zeros_like) re-apply as captured; scalar leaves (optax step
            # counts) were single-device uncommitted on the fresh path —
            # commit them replicated so no mixed-device-set conflict
            opt_state = place(
                lambda x, sh: spmd.put(
                    x, sh if len(sh.device_set) > 1 else rep),
                opt_state, opt_shardings)
            rng = spmd.put(rng, rep)

        samples = int(xs.shape[0]) * self.batch_size
        pipe = _EpochPipeline(self, samples)
        for epoch in range(start_epoch, self.num_epoch):
            variables, opt_state, rng, losses = self._profiled_run(
                run, epoch, variables, opt_state, rng, xs, ys)
            pipe.push(epoch, losses)
            if ckpt is not None:  # note: saving implies a per-epoch sync
                ckpt.save(epoch, (variables, opt_state, rng), {"epoch": epoch})
        pipe.flush()
        return self._collect_pipeline(variables, a, g, S)

    def _collect_pipeline(self, variables, a, g, S) -> Model:
        """Regroup trained pre/stages/post back into the Sequential's flat
        per-layer params list."""
        host = jax.tree_util.tree_map(_to_host, variables)
        pre = host["params"]["pre"]
        stacked = host["params"]["stages"]
        post = host["params"]["post"]
        stages_flat = []
        for i in range(S):
            group = jax.tree_util.tree_map(lambda l: l[i], stacked)
            stages_flat.extend(group)
        params = list(pre) + stages_flat + list(post)
        state = list(host["state"]["pre"]) + list(self._stage_state_full) \
            + list(host["state"]["post"])
        self.trained_variables = {"params": params, "state": state}
        self.model.variables = self.trained_variables
        return self.model


class AsynchronousDistributedTrainer(DistributedTrainer):
    """Base for the asynchronous algorithm family (reference
    ``AsynchronousDistributedTrainer``).  In sync mode these run their
    synchronous limit; ``mode='async'`` gives faithful staleness semantics
    via the host PS."""


class DOWNPOUR(AsynchronousDistributedTrainer):
    """DOWNPOUR SGD (Dean et al. 2012; reference ``DOWNPOUR`` trainer)."""

    _default_window = 5
    _async_mode = "pull_commit"

    def _sync_algorithm(self):
        return DownpourSync()

    def _ps_factory(self):
        from .ps.servers import DeltaParameterServer
        return DeltaParameterServer


class ADAG(AsynchronousDistributedTrainer):
    """ADAG — asynchronous distributed adaptive gradients (reference
    ``ADAG`` trainer; the upstream README's recommended algorithm).  The
    synchronous limit is allreduce-mean windowed SGD: the flagship TPU
    configuration."""

    _default_window = 12
    _async_mode = "pull_commit"

    def _sync_algorithm(self):
        return AdagSync()

    def _ps_factory(self):
        from .ps.servers import ADAGParameterServer
        return ADAGParameterServer


class DynSGD(AsynchronousDistributedTrainer):
    """DynSGD — staleness-aware dynamic SGD (reference ``DynSGD`` trainer +
    ``DynSGDParameterServer``): commits scaled by 1/(staleness+1)."""

    _default_window = 5
    _async_mode = "staleness"

    def _sync_algorithm(self):
        return DynSgdSync()

    def _ps_factory(self):
        from .ps.servers import DynSGDParameterServer
        return DynSGDParameterServer


class AEASGD(AsynchronousDistributedTrainer):
    """Asynchronous elastic averaging SGD (Zhang et al. 2015; reference
    ``AEASGD`` trainer).  ``rho`` is the elastic force coefficient; the
    elastic alpha is ``rho * learning_rate`` as in the reference."""

    _default_window = 32
    _async_mode = "elastic"

    def __init__(self, keras_model, worker_optimizer="sgd",
                 loss="categorical_crossentropy", num_workers: int = 2,
                 rho: float = 5.0, learning_rate: float = 0.01, **kw):
        super().__init__(keras_model, worker_optimizer, loss, num_workers,
                         learning_rate=learning_rate, **kw)
        self.rho = float(rho)

    @property
    def alpha(self) -> float:
        return self.rho * self.learning_rate

    def _sync_algorithm(self):
        return EasgdSync(self.alpha)

    def _ps_factory(self):
        from .ps.servers import DeltaParameterServer
        return DeltaParameterServer


class EAMSGD(AEASGD):
    """Elastic averaging with (Nesterov) momentum (reference ``EAMSGD``):
    identical elastic exchange, Nesterov momentum in the local optimizer."""

    def __init__(self, keras_model, worker_optimizer="sgd",
                 loss="categorical_crossentropy", num_workers: int = 2,
                 rho: float = 5.0, learning_rate: float = 0.01,
                 momentum: float = 0.9, **kw):
        if not (worker_optimizer == "sgd" or worker_optimizer is None):
            raise ValueError(
                "EAMSGD defines its own local optimizer (Nesterov-momentum "
                "SGD, per the algorithm); worker_optimizer must be left as "
                f"'sgd', got {worker_optimizer!r}")
        super().__init__(keras_model, "sgd", loss, num_workers,
                         rho=rho, learning_rate=learning_rate, **kw)
        self.momentum = float(momentum)

    def _resolve(self):
        loss_fn, _ = super()._resolve()
        optimizer = optax.sgd(self.learning_rate, momentum=self.momentum,
                              nesterov=True)
        return loss_fn, optimizer

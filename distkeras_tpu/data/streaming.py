"""Disk-backed streaming input — SURVEY.md §7 hard part 6.

The in-memory ``Dataset`` holds every column as one ndarray — fine for
MNIST/CIFAR, wrong for ImageNet-scale inputs (BASELINE config 5).  This
module streams batches from a directory of ``.npz`` shards with bounded
host memory: at any moment only the current shard plus a small prefetch
queue is resident.

Two pipeline engines, same iterator contract:

* ``"tfdata"`` — ``tf.data`` (installed in this image): shard files →
  ``from_generator`` → ``prefetch(AUTOTUNE)``; the background threading,
  autotuning and fusion come from tf.data's runtime.
* ``"thread"`` — dependency-free fallback: a producer thread reads shards
  and slices batches into a bounded ``queue.Queue`` so disk IO overlaps
  device compute.

``SingleTrainer.train`` accepts a ``ShardedFileDataset`` directly: epochs
stream window-by-window from disk while the TPU trains the previous
window (the trainer never materializes an epoch in RAM).
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import Iterator, Optional, Sequence

import numpy as np

_META = "meta.json"


class ShardedFileDataset:
    """A directory of row-aligned ``.npz`` shards + a ``meta.json``.

    Create one with :meth:`write` (from any in-memory ``Dataset``) or point
    it at an existing directory produced by another writer (each shard: one
    ``.npz`` with identical keys; meta lists shards in order).
    """

    def __init__(self, directory: str):
        self.directory = directory
        with open(os.path.join(directory, _META)) as f:
            meta = json.load(f)
        self.shards: list = meta["shards"]
        self.num_rows: int = int(meta["num_rows"])
        self.column_names: list = meta["columns"]
        self._tf_spec_cache: dict = {}  # (cols, batch) -> TensorSpec tuple

    # -- construction -------------------------------------------------------
    @staticmethod
    def write(dataset, directory: str,
              rows_per_shard: int = 4096) -> "ShardedFileDataset":
        """Spill an in-memory ``Dataset`` to disk shards."""
        os.makedirs(directory, exist_ok=True)
        shards = []
        for i, lo in enumerate(range(0, dataset.num_rows, rows_per_shard)):
            hi = min(lo + rows_per_shard, dataset.num_rows)
            name = f"shard_{i:05d}.npz"
            np.savez(os.path.join(directory, name),
                     **{c: dataset[c][lo:hi] for c in dataset.column_names})
            shards.append(name)
        with open(os.path.join(directory, _META), "w") as f:
            json.dump({"shards": shards, "num_rows": dataset.num_rows,
                       "columns": dataset.column_names}, f)
        return ShardedFileDataset(directory)

    # -- iteration ----------------------------------------------------------
    def steps_per_epoch(self, batch_size: int) -> int:
        return self.num_rows // batch_size

    def _load(self, name: str) -> dict:
        with np.load(os.path.join(self.directory, name)) as d:
            return {k: d[k] for k in d.files}

    def _batch_source(self, cols: Sequence[str], batch_size: int,
                      seed: Optional[int]) -> Iterator[tuple]:
        """Sequential batch generator: shard order (optionally shuffled per
        epoch), rows carried across shard boundaries, remainder dropped
        (static shapes — SURVEY.md §7 XLA recompilation trap)."""
        order = list(range(len(self.shards)))
        if seed is not None:
            np.random.default_rng(seed).shuffle(order)
        carry = None
        for si in order:
            shard = self._load(self.shards[si])
            if seed is not None:
                perm = np.random.default_rng((seed, si)).permutation(
                    len(shard[cols[0]]))
                shard = {k: v[perm] for k, v in shard.items()}
            arrs = [shard[c] for c in cols]
            if carry is not None:
                arrs = [np.concatenate([c, a]) for c, a in zip(carry, arrs)]
            n = arrs[0].shape[0]
            nb = n // batch_size
            for b in range(nb):
                yield tuple(a[b * batch_size:(b + 1) * batch_size]
                            for a in arrs)
            rem = n - nb * batch_size
            carry = [a[n - rem:] for a in arrs] if rem else None

    def batches(self, cols: Sequence[str], batch_size: int,
                engine: str = "auto", prefetch: int = 4,
                seed: Optional[int] = None) -> Iterator[tuple]:
        """Stream ``(col_0, col_1, ...)`` batch tuples from disk."""
        if engine == "auto":
            engine = "tfdata" if _has_tf() else "thread"
        if engine == "tfdata":
            return self._tfdata_batches(cols, batch_size, prefetch, seed)
        if engine == "thread":
            return _prefetched(self._batch_source(cols, batch_size, seed),
                               prefetch)
        raise ValueError(f"engine must be auto|tfdata|thread, got {engine!r}")

    def _tfdata_batches(self, cols, batch_size, prefetch, seed):
        import tensorflow as tf
        gen = lambda: self._batch_source(cols, batch_size, seed)  # noqa: E731
        # shapes/dtypes don't change per epoch: probe once per
        # (cols, batch) and cache — the probe reads a whole shard, which
        # the per-epoch caller must not pay repeatedly
        key = (tuple(cols), batch_size)
        spec = self._tf_spec_cache.get(key)
        if spec is None:
            probe = next(self._batch_source(cols, batch_size, None))
            spec = tuple(tf.TensorSpec((batch_size, *a.shape[1:]), a.dtype)
                         for a in probe)
            self._tf_spec_cache[key] = spec
        ds = tf.data.Dataset.from_generator(gen, output_signature=spec)
        ds = ds.prefetch(tf.data.AUTOTUNE)
        return ((tuple(t.numpy() for t in item)) for item in ds)


def _has_tf() -> bool:
    try:
        import tensorflow  # noqa: F401
        return True
    except ImportError:  # pragma: no cover
        return False


def _prefetched(it: Iterator, depth: int) -> Iterator:
    """Run ``it`` in a producer thread with a bounded queue: disk reads
    overlap consumer (device) work; memory stays bounded at ``depth``
    batches.

    The consumer may abandon the iterator mid-epoch (the trainer takes
    exactly ``n_windows * w`` batches and drops the rest): generator
    close/GC sets ``stop``, the producer's blocked ``put`` times out and
    the thread exits instead of pinning the current shard forever."""
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    _END = object()
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for item in it:
                if not put(item):
                    return
            put(_END)
        except BaseException as e:  # surfaced on the consumer side
            put(e)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()

"""Disk-backed streaming input — SURVEY.md §7 hard part 6.

The in-memory ``Dataset`` holds every column as one ndarray — fine for
MNIST/CIFAR, wrong for ImageNet-scale inputs (BASELINE config 5).  This
module streams batches from a directory of ``.npz`` shards with bounded
host memory: at any moment only the current shard plus a small prefetch
queue is resident.

Two pipeline engines, same iterator contract:

* ``"tfdata"`` — ``tf.data`` (installed in this image): shard files →
  ``from_generator`` → ``prefetch(AUTOTUNE)``; the background threading,
  autotuning and fusion come from tf.data's runtime.
* ``"thread"`` — dependency-free fallback: a producer thread reads shards
  and slices batches into a bounded ``queue.Queue`` so disk IO overlaps
  device compute.

``SingleTrainer.train`` accepts a ``ShardedFileDataset`` directly: epochs
stream window-by-window from disk while the TPU trains the previous
window (the trainer never materializes an epoch in RAM).

Instrumented (ISSUE 2, process-wide default registry): ``stream.batches``
counts batches handed to consumers, ``stream.stall_seconds`` accumulates
time a consumer sat blocked on an empty prefetch queue (the disk-bound
signal: nonzero stall with full occupancy elsewhere means IO can't keep
up with the device), ``stream.prefetch_occupancy`` gauges queue depth.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Iterator, Optional, Sequence

import numpy as np

from ..obs import default_registry

_META = "meta.json"


class ShardedFileDataset:
    """A directory of row-aligned ``.npz`` shards + a ``meta.json``.

    Create one with :meth:`write` (from any in-memory ``Dataset``) or point
    it at an existing directory produced by another writer (each shard: one
    ``.npz`` with identical keys; meta lists shards in order).
    """

    def __init__(self, directory: str):
        self.directory = directory
        with open(os.path.join(directory, _META)) as f:
            meta = json.load(f)
        self.shards: list = meta["shards"]
        self.num_rows: int = int(meta["num_rows"])
        self.column_names: list = meta["columns"]
        self._shard_rows: Optional[list] = meta.get("shard_rows")
        self._tf_spec_cache: dict = {}  # (cols, batch) -> TensorSpec tuple

    # -- construction -------------------------------------------------------
    @staticmethod
    def write(dataset, directory: str,
              rows_per_shard: int = 4096) -> "ShardedFileDataset":
        """Spill an in-memory ``Dataset`` to disk shards."""
        os.makedirs(directory, exist_ok=True)
        shards, shard_rows = [], []
        for i, lo in enumerate(range(0, dataset.num_rows, rows_per_shard)):
            hi = min(lo + rows_per_shard, dataset.num_rows)
            name = f"shard_{i:05d}.npz"
            np.savez(os.path.join(directory, name),
                     **{c: dataset[c][lo:hi] for c in dataset.column_names})
            shards.append(name)
            shard_rows.append(hi - lo)
        with open(os.path.join(directory, _META), "w") as f:
            json.dump({"shards": shards, "num_rows": dataset.num_rows,
                       "columns": dataset.column_names,
                       "shard_rows": shard_rows}, f)
        return ShardedFileDataset(directory)

    # -- iteration ----------------------------------------------------------
    def steps_per_epoch(self, batch_size: int) -> int:
        return self.num_rows // batch_size

    def shard_rows(self) -> list:
        """Per-shard row counts.  Written into ``meta.json`` by
        :meth:`write`; for directories from other writers, probed once by
        reading each shard's first ``.npy`` header (no array data)."""
        if self._shard_rows is None:
            import zipfile
            col0 = self.column_names[0] + ".npy"
            rows = []
            for name in self.shards:
                with zipfile.ZipFile(
                        os.path.join(self.directory, name)) as z, \
                        z.open(col0) as f:
                    version = np.lib.format.read_magic(f)
                    if version == (1, 0):
                        shape, _, _ = np.lib.format.read_array_header_1_0(f)
                    else:
                        shape, _, _ = np.lib.format.read_array_header_2_0(f)
                    rows.append(int(shape[0]))
            self._shard_rows = rows
        return self._shard_rows

    # -- per-worker partitioning (Spark partition == worker; SURVEY.md §3.1
    # boundary #1: each executor streams ITS files, never the whole set) ----
    def worker_shard_indices(self, worker: int, num_workers: int) -> list:
        """Round-robin shard → worker assignment (shard i → worker i % P).
        With ``rows_per_shard = num_rows // P`` this reproduces the
        in-memory ``Dataset.repartition(P)`` contiguous split exactly."""
        if not (0 <= worker < num_workers):
            raise ValueError(f"worker {worker} outside [0, {num_workers})")
        if len(self.shards) < num_workers:
            raise ValueError(
                f"{len(self.shards)} shards cannot feed {num_workers} "
                f"workers (need >= one shard per worker; re-write with "
                f"rows_per_shard <= {self.num_rows // num_workers})")
        return list(range(worker, len(self.shards), num_workers))

    def worker_rows(self, worker: int, num_workers: int) -> int:
        rows = self.shard_rows()
        return sum(rows[i] for i in
                   self.worker_shard_indices(worker, num_workers))

    def worker_steps_per_epoch(self, batch_size: int,
                               num_workers: int) -> int:
        """Common per-worker step count: min over workers (static shapes —
        every worker must run the same number of jit steps per epoch)."""
        return min(self.worker_rows(k, num_workers) // batch_size
                   for k in range(num_workers))

    def worker_batches(self, cols: Sequence[str], batch_size: int,
                       worker: int, num_workers: int,
                       engine: str = "thread", prefetch: int = 4,
                       seed: Optional[int] = None) -> Iterator[tuple]:
        """Stream batches drawn only from ``worker``'s shard partition.
        ``seed`` is decorrelated per worker (shard order + in-shard perm).
        ``engine="thread"`` (default) prefetches in a producer thread;
        ``"raw"`` iterates synchronously (a caller that already overlaps
        IO, e.g. a worker thread of its own)."""
        idx = self.worker_shard_indices(worker, num_workers)
        wseed = None if seed is None else (seed * num_workers + worker + 1)
        src = self._batch_source(cols, batch_size, wseed, shard_indices=idx)
        if engine == "thread":
            return _prefetched(src, prefetch)
        if engine == "raw":
            return src
        raise ValueError(f"engine must be thread|raw, got {engine!r}")

    def _load(self, name: str) -> dict:
        with np.load(os.path.join(self.directory, name)) as d:
            return {k: d[k] for k in d.files}

    def _batch_source(self, cols: Sequence[str], batch_size: int,
                      seed: Optional[int],
                      shard_indices: Optional[Sequence[int]] = None
                      ) -> Iterator[tuple]:
        """Sequential batch generator: shard order (optionally shuffled per
        epoch), rows carried across shard boundaries, remainder dropped
        (static shapes — SURVEY.md §7 XLA recompilation trap)."""
        order = list(shard_indices) if shard_indices is not None \
            else list(range(len(self.shards)))
        if seed is not None:
            np.random.default_rng(seed).shuffle(order)
        carry = None
        for si in order:
            shard = self._load(self.shards[si])
            if seed is not None:
                perm = np.random.default_rng((seed, si)).permutation(
                    len(shard[cols[0]]))
                shard = {k: v[perm] for k, v in shard.items()}
            arrs = [shard[c] for c in cols]
            if carry is not None:
                arrs = [np.concatenate([c, a]) for c, a in zip(carry, arrs)]
            n = arrs[0].shape[0]
            nb = n // batch_size
            for b in range(nb):
                yield tuple(a[b * batch_size:(b + 1) * batch_size]
                            for a in arrs)
            rem = n - nb * batch_size
            carry = [a[n - rem:] for a in arrs] if rem else None

    def batches(self, cols: Sequence[str], batch_size: int,
                engine: str = "auto", prefetch: int = 4,
                seed: Optional[int] = None) -> Iterator[tuple]:
        """Stream ``(col_0, col_1, ...)`` batch tuples from disk."""
        if engine == "auto":
            engine = "tfdata" if _has_tf() else "thread"
        if engine == "tfdata":
            return self._tfdata_batches(cols, batch_size, prefetch, seed)
        if engine == "thread":
            return _prefetched(self._batch_source(cols, batch_size, seed),
                               prefetch)
        raise ValueError(f"engine must be auto|tfdata|thread, got {engine!r}")

    def _tfdata_batches(self, cols, batch_size, prefetch, seed):
        import tensorflow as tf
        gen = lambda: self._batch_source(cols, batch_size, seed)  # noqa: E731
        # shapes/dtypes don't change per epoch: probe once per
        # (cols, batch) and cache — the probe reads a whole shard, which
        # the per-epoch caller must not pay repeatedly
        key = (tuple(cols), batch_size)
        spec = self._tf_spec_cache.get(key)
        if spec is None:
            probe = next(self._batch_source(cols, batch_size, None))
            spec = tuple(tf.TensorSpec((batch_size, *a.shape[1:]), a.dtype)
                         for a in probe)
            self._tf_spec_cache[key] = spec
        ds = tf.data.Dataset.from_generator(gen, output_signature=spec)
        ds = ds.prefetch(tf.data.AUTOTUNE)
        c_batches = default_registry().counter("stream.batches")

        def consume():
            for item in ds:
                c_batches.inc()
                yield tuple(t.numpy() for t in item)
        return consume()


def window_batches(it: Iterator[tuple], window: int) -> Iterator[tuple]:
    """Group ``window`` consecutive batch tuples into one tuple of stacked
    arrays with a leading ``(window,)`` axis — the host-side assembly of a
    communication window (trainers feed these to one jit window program).
    A trailing partial window is dropped (static shapes)."""
    import itertools
    try:
        while True:
            group = list(itertools.islice(it, window))
            if len(group) < window:
                return
            yield tuple(np.stack(col) for col in zip(*group))
    finally:
        # deterministic teardown: a consumer that abandons the epoch early
        # must release the source's prefetch thread/shard immediately
        if hasattr(it, "close"):
            it.close()


def worker_windows_per_epoch(source: "ShardedFileDataset", batch_size: int,
                             num_workers: int, window: int) -> int:
    """Common per-worker window count per epoch, validated — the single
    arithmetic every streaming consumer (sync trainer, async runner) uses."""
    steps = source.worker_steps_per_epoch(batch_size, num_workers)
    n = steps // window
    if n == 0:
        raise ValueError(
            f"communication_window {window} exceeds the {steps} steps "
            f"available per worker (decrease window/batch_size or add data)")
    return n


def worker_window_factory(source: "ShardedFileDataset", cols: Sequence[str],
                          batch_size: int, worker: int, num_workers: int,
                          window: int, base_seed: int, shuffle: bool):
    """``factory(epoch) -> iterator`` of stacked ``(window, batch, ...)``
    column tuples over ``worker``'s shard partition.

    This is THE shared recipe — per-epoch seed derivation included — for
    all three streaming consumers (sync trainer loop, async thread
    workers, async process workers): one formula, so data order stays
    bit-identical across placements."""
    def make(epoch: int):
        seed = (base_seed + 1000 + epoch) if shuffle else None
        return window_batches(
            source.worker_batches(cols, batch_size, worker, num_workers,
                                  seed=seed), window)
    return make


def _has_tf() -> bool:
    try:
        import tensorflow  # noqa: F401
        return True
    except ImportError:  # pragma: no cover
        return False


def _prefetched(it: Iterator, depth: int) -> Iterator:
    """Run ``it`` in a producer thread with a bounded queue: disk reads
    overlap consumer (device) work; memory stays bounded at ``depth``
    batches.

    The consumer may abandon the iterator mid-epoch (the trainer takes
    exactly ``n_windows * w`` batches and drops the rest): generator
    close/GC sets ``stop``, the producer's blocked ``put`` times out and
    the thread exits instead of pinning the current shard forever; a
    bounded ``join`` then confirms the exit (ISSUE 3 thread-shutdown
    rule), so a run's teardown never leaves producers racing interpreter
    shutdown with a shard file half-read."""
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    _END = object()
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for item in it:
                if not put(item):
                    return
            put(_END)
        except BaseException as e:  # surfaced on the consumer side
            put(e)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    reg = default_registry()
    c_batches = reg.counter("stream.batches")
    c_stall = reg.counter("stream.stall_seconds")
    g_occ = reg.gauge("stream.prefetch_occupancy")
    try:
        while True:
            t0 = time.perf_counter()
            item = q.get()  # blocks only when the producer is behind
            c_stall.inc(time.perf_counter() - t0)
            g_occ.set(q.qsize())
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            c_batches.inc()
            yield item
    finally:
        stop.set()
        # bounded: the producer notices `stop` within one 0.1 s put
        # timeout; the slack covers an in-flight shard read.  A producer
        # still alive after this is surfaced, not silently abandoned.
        t.join(timeout=2.0)
        if t.is_alive():  # pragma: no cover - pathological IO stall
            default_registry().counter("stream.producer_leaks").inc()

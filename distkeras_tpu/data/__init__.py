from .dataset import Dataset
from .transformers import (
    Transformer, OneHotTransformer, MinMaxTransformer, ReshapeTransformer,
    DenseTransformer, LabelIndexTransformer,
)

"""Host-side sharded dataset — the Spark-DataFrame replacement.

The reference leans on Spark for everything data-shaped: named columns,
``repartition(num_workers)``, ``rdd.mapPartitionsWithIndex`` to hand each
worker its partition iterator, and driver-side ``collect`` (reference
``distkeras/trainers.py:DistributedTrainer.train``).  On TPU there is no
JVM: we keep a column-oriented in-memory table with explicit partitions.
Partition k feeds worker/chip k; for the SPMD sync path partitions become
the leading device axis of one stacked array so batches transfer host→HBM
in a single ``device_put``.

Columns are NumPy arrays (row-aligned).  All ops are cheap views/indexing —
no copies unless necessary.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Sequence

import numpy as np


class Dataset:
    """Column-oriented table with Spark-like partitioning semantics."""

    def __init__(self, columns: Dict[str, np.ndarray], num_partitions: int = 1):
        if not columns:
            raise ValueError("Dataset needs at least one column")
        n = None
        self.columns: Dict[str, np.ndarray] = {}
        for k, v in columns.items():
            v = np.asarray(v)
            if n is None:
                n = v.shape[0]
            elif v.shape[0] != n:
                raise ValueError(f"column {k!r} has {v.shape[0]} rows, expected {n}")
            self.columns[k] = v
        self.num_rows = int(n)
        self.num_partitions = max(1, min(int(num_partitions), self.num_rows))

    # -- construction -------------------------------------------------------
    @classmethod
    def from_arrays(cls, **columns) -> "Dataset":
        return cls(columns)

    @classmethod
    def from_csv(cls, path: str, num_features: int,
                 label_col: str = "label", features_col: str = "features",
                 label_first: bool = True, nthreads: int = 0) -> "Dataset":
        """Load a numeric CSV of ``num_features + 1`` columns per row (the
        reference's MNIST-CSV shape: label + flat pixels) via the native
        multithreaded parser (``native/dknative.cpp``), NumPy fallback.
        """
        from ..utils import native
        flat = native.parse_csv(path, nthreads)
        width = num_features + 1
        if flat.size % width:
            raise ValueError(
                f"CSV value count {flat.size} not divisible by row width "
                f"{width}")
        rows = flat.reshape(-1, width)
        if label_first:
            labels, feats = rows[:, 0], rows[:, 1:]
        else:
            labels, feats = rows[:, -1], rows[:, :-1]
        return cls({features_col: np.ascontiguousarray(feats),
                    label_col: labels.astype(np.int64)})

    # -- Spark-surface ops --------------------------------------------------
    def repartition(self, n: int) -> "Dataset":
        """Parity: ``df.repartition(num_workers)``."""
        return Dataset(self.columns, num_partitions=n)

    def coalesce(self, n: int) -> "Dataset":
        return self.repartition(n)

    def shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Parity: ``distkeras/utils.py:shuffle(df)`` (random row order)."""
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.num_rows)
        return Dataset({k: v[perm] for k, v in self.columns.items()},
                       self.num_partitions)

    def select(self, *cols: str) -> "Dataset":
        return Dataset({c: self.columns[c] for c in cols}, self.num_partitions)

    def with_column(self, name: str, values: np.ndarray) -> "Dataset":
        cols = dict(self.columns)
        cols[name] = np.asarray(values)
        return Dataset(cols, self.num_partitions)

    def drop(self, *cols: str) -> "Dataset":
        return Dataset({k: v for k, v in self.columns.items() if k not in cols},
                       self.num_partitions)

    def take(self, n: int) -> "Dataset":
        return Dataset({k: v[:n] for k, v in self.columns.items()},
                       self.num_partitions)

    def count(self) -> int:
        return self.num_rows

    def __len__(self) -> int:
        return self.num_rows

    @property
    def column_names(self) -> list:
        return list(self.columns)

    # -- partition access ---------------------------------------------------
    def _bounds(self) -> np.ndarray:
        return np.linspace(0, self.num_rows, self.num_partitions + 1).astype(int)

    def partition(self, i: int) -> Dict[str, np.ndarray]:
        """Columns of partition ``i`` (views, no copy)."""
        b = self._bounds()
        return {k: v[b[i]:b[i + 1]] for k, v in self.columns.items()}

    def partitions(self) -> Iterator[Dict[str, np.ndarray]]:
        for i in range(self.num_partitions):
            yield self.partition(i)

    def partition_sizes(self) -> list:
        b = self._bounds()
        return [int(b[i + 1] - b[i]) for i in range(self.num_partitions)]

    def stacked(self, cols: Sequence[str], batch_size: int):
        """Device-axis view for the SPMD sync path.

        Truncates each partition to a common multiple of ``batch_size`` and
        returns ``{col: array of shape (P, steps, batch, ...)}`` plus the
        step count — ready to reshard over a ``Mesh`` in one transfer.
        """
        per = min(self.partition_sizes())
        steps = per // batch_size
        if steps == 0:
            raise ValueError(
                f"batch_size {batch_size} larger than smallest partition {per}")
        out = {}
        for c in cols:
            parts = [p[c][: steps * batch_size] for p in
                     (self.partition(i) for i in range(self.num_partitions))]
            arr = np.stack(parts)  # (P, steps*batch, ...)
            out[c] = arr.reshape(self.num_partitions, steps, batch_size,
                                 *arr.shape[2:])
        return out, steps

    # -- row access (predictors / transformers) -----------------------------
    def rows(self) -> Iterator[Dict[str, np.ndarray]]:
        for i in range(self.num_rows):
            yield {k: v[i] for k, v in self.columns.items()}

    def __getitem__(self, col: str) -> np.ndarray:
        return self.columns[col]

    def __repr__(self):
        cols = ", ".join(f"{k}:{v.shape[1:]}:{v.dtype}" for k, v in self.columns.items())
        return (f"Dataset(rows={self.num_rows}, partitions={self.num_partitions}, "
                f"cols=[{cols}])")

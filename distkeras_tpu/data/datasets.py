"""Benchmark dataset loaders — MNIST / CIFAR-10 / IMDB / ImageNet-subset.

The reference reads its data from CSV/parquet on HDFS via Spark (the MNIST
notebook loads a CSV of flat pixels).  Here loaders return our partitioned
``Dataset`` directly.  In an air-gapped environment the real archives may
be absent: each loader first tries the local Keras cache
(``~/.keras/datasets``), then falls back to a **deterministic synthetic
surrogate** with the same shapes/dtypes and a learnable class structure
(class-template + noise), flagged via ``meta['synthetic']`` — throughput
benchmarks are unaffected and convergence checks remain meaningful.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from .dataset import Dataset

KERAS_CACHE = os.path.expanduser("~/.keras/datasets")


def _synthetic_images(n: int, shape: Tuple[int, ...], num_classes: int,
                      seed: int, noise: float = 0.35, split_seed: int = 0):
    """Class-template images: templates are smooth random fields; samples =
    template[label] + gaussian noise.  Linearly separable enough to train
    on, hard enough that accuracy tracks real optimization progress.

    ``seed`` fixes the class templates (MUST be shared by the train and
    test splits of one dataset, or test accuracy is chance);
    ``split_seed`` varies the sampled labels/noise per split.
    """
    rng = np.random.default_rng(seed)
    templates = rng.normal(0.5, 0.25, size=(num_classes, *shape)).astype(np.float32)
    srng = np.random.default_rng((seed, split_seed))
    labels = srng.integers(0, num_classes, size=n)
    x = templates[labels] + srng.normal(0, noise, size=(n, *shape)).astype(np.float32)
    return np.clip(x, 0.0, 1.0).astype(np.float32), labels.astype(np.int64)


def load_mnist(n_train: Optional[int] = None, flat: bool = True,
               seed: int = 0, noise: float = 0.35,
               label_noise: float = 0.0) -> Tuple[Dataset, Dataset, dict]:
    """(train, test, meta).  Columns: ``features`` (784 flat or 28×28×1),
    ``label`` int.  Pixels already scaled to [0,1] (the reference pipeline
    does this with ``MinMaxTransformer``; loaders pre-scale so benchmarks
    measure training, not preprocessing).

    Difficulty levers for the convergence gate (VERDICT r3 weak #5 — a
    surrogate every trainer aces cannot discriminate): ``noise`` is the
    synthetic surrogate's pixel-noise sigma; ``label_noise`` uniformly
    relabels that fraction of TRAIN rows (test labels stay clean, so test
    accuracy still measures what was actually learned).  Defaults keep
    the historical benchmark behavior."""
    path = os.path.join(KERAS_CACHE, "mnist.npz")
    meta = {"num_classes": 10, "synthetic": True}
    if os.path.exists(path):
        with np.load(path) as d:
            xtr, ytr = d["x_train"], d["y_train"]
            xte, yte = d["x_test"], d["y_test"]
        xtr = (xtr / 255.0).astype(np.float32)
        xte = (xte / 255.0).astype(np.float32)
        meta["synthetic"] = False
    else:
        xtr, ytr = _synthetic_images(n_train or 60000, (28, 28), 10, seed,
                                     split_seed=0, noise=noise)
        xte, yte = _synthetic_images(10000, (28, 28), 10, seed, split_seed=1,
                                     noise=noise)
    if n_train:
        xtr, ytr = xtr[:n_train], ytr[:n_train]
    if label_noise:
        nrng = np.random.default_rng((seed, 104))
        flip = nrng.random(len(ytr)) < label_noise
        ytr = np.where(flip, nrng.integers(0, 10, size=len(ytr)), ytr)
    if flat:
        xtr = xtr.reshape(len(xtr), 784)
        xte = xte.reshape(len(xte), 784)
    else:
        xtr = xtr.reshape(len(xtr), 28, 28, 1)
        xte = xte.reshape(len(xte), 28, 28, 1)
    return (Dataset({"features": xtr, "label": ytr}),
            Dataset({"features": xte, "label": yte}), meta)


def load_cifar10(n_train: Optional[int] = None, seed: int = 0
                 ) -> Tuple[Dataset, Dataset, dict]:
    """(train, test, meta).  ``features`` 32×32×3 float32 in [0,1]."""
    path = os.path.join(KERAS_CACHE, "cifar-10-batches-py")
    meta = {"num_classes": 10, "synthetic": True}
    if os.path.isdir(path):
        import pickle
        xs, ys = [], []
        for i in range(1, 6):
            with open(os.path.join(path, f"data_batch_{i}"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        xtr = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        xtr = (xtr / 255.0).astype(np.float32)
        ytr = np.asarray(ys, dtype=np.int64)
        with open(os.path.join(path, "test_batch"), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xte = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        xte = (xte / 255.0).astype(np.float32)
        yte = np.asarray(d[b"labels"], dtype=np.int64)
        meta["synthetic"] = False
    else:
        xtr, ytr = _synthetic_images(n_train or 50000, (32, 32, 3), 10, seed,
                                     split_seed=0)
        xte, yte = _synthetic_images(10000, (32, 32, 3), 10, seed,
                                     split_seed=1)
    if n_train:
        xtr, ytr = xtr[:n_train], ytr[:n_train]
    return (Dataset({"features": xtr, "label": ytr}),
            Dataset({"features": xte, "label": yte}), meta)


def load_imdb(n_train: Optional[int] = None, seq_len: int = 200,
              vocab_size: int = 20000, seed: int = 0
              ) -> Tuple[Dataset, Dataset, dict]:
    """(train, test, meta).  ``features`` int32 token ids padded/truncated
    to ``seq_len``; ``label`` in {0,1}.  Synthetic surrogate: two Zipfian
    token distributions with class-indicative marker tokens."""
    path = os.path.join(KERAS_CACHE, "imdb.npz")
    meta = {"num_classes": 2, "synthetic": True, "seq_len": seq_len}

    OOV = 2  # Keras imdb convention: oov_char=2

    def pad(seqs):
        out = np.zeros((len(seqs), seq_len), dtype=np.int32)
        for i, s in enumerate(seqs):
            s = np.asarray(s[:seq_len], dtype=np.int32)
            s = np.where(s < vocab_size, s, OOV)
            out[i, : len(s)] = s
        return out

    if os.path.exists(path):
        with np.load(path, allow_pickle=True) as d:
            xtr, ytr = pad(d["x_train"]), d["y_train"].astype(np.int64)
            xte, yte = pad(d["x_test"]), d["y_test"].astype(np.int64)
        meta["synthetic"] = False
    else:
        def synth(n, s):
            rng = np.random.default_rng(s)
            labels = rng.integers(0, 2, size=n)
            # Zipf-ish body + class-marker tokens sprinkled in
            body = rng.zipf(1.3, size=(n, seq_len)).astype(np.int64)
            body = np.clip(body, 1, vocab_size - 1)
            markers = np.where(labels[:, None] == 1, 17, 23)
            mask = rng.random((n, seq_len)) < 0.08
            x = np.where(mask, markers, body).astype(np.int32)
            return x, labels.astype(np.int64)
        xtr, ytr = synth(n_train or 25000, seed)
        xte, yte = synth(5000, seed + 1)
    if n_train:
        xtr, ytr = xtr[:n_train], ytr[:n_train]
    return (Dataset({"features": xtr, "label": ytr}),
            Dataset({"features": xte, "label": yte}), meta)


def load_lm_corpus(n_train: int = 2048, seq_len: int = 256,
                   vocab_size: int = 64, seed: int = 0
                   ) -> Tuple[Dataset, Dataset, dict]:
    """(train, test, meta) for the long-context causal-LM config
    (``zoo.gpt_lm`` — beyond the reference, SURVEY.md §5.7).  Synthetic
    counting corpus: token t+1 = (token t + 1) mod vocab.  ``features``
    int32 ``(seq_len,)`` token ids; ``label`` int64 ``(seq_len,)`` is the
    sequence shifted left by one (next-token targets)."""
    def split(n, s):
        start = np.random.default_rng(s).integers(0, vocab_size, size=n)
        seqs = (start[:, None] + np.arange(seq_len + 1)) % vocab_size
        return Dataset({"features": seqs[:, :-1].astype(np.int32),
                        "label": seqs[:, 1:].astype(np.int64)})
    meta = {"vocab_size": vocab_size, "seq_len": seq_len, "synthetic": True}
    return split(n_train, seed), split(max(n_train // 4, 1), seed + 1), meta


def load_imagenet_subset(n_train: int = 5000, num_classes: int = 100,
                         image_size: int = 224, seed: int = 0
                         ) -> Tuple[Dataset, Dataset, dict]:
    """(train, test, meta) for the DynSGD ResNet-50 config.  Always
    synthetic in this environment (no ImageNet on disk): ``features``
    ``image_size²×3`` float32."""
    meta = {"num_classes": num_classes, "synthetic": True}
    xtr, ytr = _synthetic_images(n_train, (image_size, image_size, 3),
                                 num_classes, seed, split_seed=0)
    xte, yte = _synthetic_images(max(n_train // 10, num_classes),
                                 (image_size, image_size, 3), num_classes,
                                 seed, split_seed=1)
    return (Dataset({"features": xtr, "label": ytr}),
            Dataset({"features": xte, "label": yte}), meta)

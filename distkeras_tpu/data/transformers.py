"""Spark-ML-style data transformers — vectorized TPU-host versions.

Parity with reference ``distkeras/transformers.py``: same class names, same
constructor arguments, same ``.transform(dataset) -> dataset`` surface.  The
reference implements each as a per-Row ``rdd.map``; ours are whole-column
NumPy ops (orders of magnitude faster on host, and the arrays land in HBM
batch-shaped).
"""

from __future__ import annotations

import numpy as np

from .dataset import Dataset


class Transformer:
    def transform(self, dataset: Dataset) -> Dataset:
        raise NotImplementedError

    def __call__(self, dataset: Dataset) -> Dataset:
        return self.transform(dataset)


class OneHotTransformer(Transformer):
    """Label index -> one-hot vector.

    Parity: reference ``distkeras/transformers.py:OneHotTransformer``
    (``to_dense_vector`` per row).
    """

    def __init__(self, output_dim: int, input_col: str = "label",
                 output_col: str = "label_encoded"):
        self.output_dim = int(output_dim)
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        labels = dataset[self.input_col].astype(np.int64).reshape(-1)
        if labels.size and (labels.min() < 0 or labels.max() >= self.output_dim):
            raise ValueError(
                f"labels must be in [0, {self.output_dim}); got range "
                f"[{labels.min()}, {labels.max()}]")
        out = np.zeros((labels.shape[0], self.output_dim), dtype=np.float32)
        out[np.arange(labels.shape[0]), labels] = 1.0
        return dataset.with_column(self.output_col, out)


class MinMaxTransformer(Transformer):
    """Range renormalization (e.g. pixels 0..255 -> 0..1).

    Parity: reference ``distkeras/transformers.py:MinMaxTransformer``.
    """

    def __init__(self, n_min: float = 0.0, n_max: float = 1.0,
                 o_min: float = 0.0, o_max: float = 255.0,
                 input_col: str = "features", output_col: str = "features_normalized"):
        self.n_min, self.n_max = float(n_min), float(n_max)
        self.o_min, self.o_max = float(o_min), float(o_max)
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        x = dataset[self.input_col].astype(np.float32)
        scale = (self.n_max - self.n_min) / (self.o_max - self.o_min)
        return dataset.with_column(self.output_col,
                                   (x - self.o_min) * scale + self.n_min)


class ReshapeTransformer(Transformer):
    """Flat vector -> tensor shape (for convnets).

    Parity: reference ``distkeras/transformers.py:ReshapeTransformer``.
    """

    def __init__(self, input_col: str, output_col: str, shape):
        self.input_col = input_col
        self.output_col = output_col
        self.shape = tuple(int(s) for s in shape)

    def transform(self, dataset: Dataset) -> Dataset:
        x = dataset[self.input_col]
        return dataset.with_column(self.output_col,
                                   x.reshape(x.shape[0], *self.shape))


class DenseTransformer(Transformer):
    """Sparse -> dense vector.  Our columns are already dense ndarrays, so
    this is an (idempotent) dtype/densify pass kept for API parity.

    Parity: reference ``distkeras/transformers.py:DenseTransformer``.
    """

    def __init__(self, input_col: str = "features", output_col: str = "features_dense"):
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        x = np.asarray(dataset[self.input_col], dtype=np.float32)
        return dataset.with_column(self.output_col, x)


class LabelIndexTransformer(Transformer):
    """Prediction vector -> argmax class index (float, like the reference).

    Parity: reference ``distkeras/transformers.py:LabelIndexTransformer``.
    """

    def __init__(self, output_dim: int = None, input_col: str = "prediction",
                 output_col: str = "prediction_index", activation_threshold: float = 0.55):
        self.output_dim = output_dim
        self.input_col = input_col
        self.output_col = output_col
        self.activation_threshold = activation_threshold

    def transform(self, dataset: Dataset) -> Dataset:
        p = dataset[self.input_col]
        if p.ndim == 1 or p.shape[-1] == 1:
            idx = (p.reshape(-1) >= self.activation_threshold).astype(np.float32)
        else:
            idx = np.argmax(p, axis=-1).astype(np.float32)
        return dataset.with_column(self.output_col, idx)
